"""Bench harness smoke: every BASELINE config measure runs at tiny
sizes on the CPU mesh and passes its own correctness guard.

The real numbers come from `python bench.py` / `--configs` on the chip
(driver artifact + BENCH_CONFIGS.json); these tests only keep the
harness importable and honest — a broken guard or a config that can't
compile should fail HERE, not in the one driver-run bench window per
round (the round-2 lesson: bench failures on the chip are expensive).
"""

import sys
import os

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402


def test_measure_nakamoto_guard():
    rate, rel, _ = bench.measure_nakamoto(64, n_steps=2200, reps=1)
    assert rate > 0
    assert bench.SM1_GUARD[0] < rel < bench.SM1_GUARD[1], rel


@pytest.mark.slow  # compiles the 3 heaviest kernels in the repo
def test_measure_config_guards():
    for name, spec in bench.CONFIGS.items():
        kw = dict(spec["cpu"])
        kw["n_envs"] = min(kw["n_envs"], 32)
        rate, check, _extras = getattr(bench, spec["fn"])(**kw, reps=1)
        lo, hi = spec["guard"]
        assert rate > 0, name
        assert lo < check < hi, (name, check)


def test_last_known_tpu_skips_outage_poisoned_banks(tmp_path):
    """An outage-tagged row claiming backend "tpu" (banked during a
    wedge) must never become the last-known-TPU context a fallback row
    ships — the newest CLEAN round wins even when a poisoned newer
    round exists."""
    import json

    def bank(name, n, row):
        (tmp_path / name).write_text(json.dumps(
            {"n": n, "tail": "", "parsed": row}))

    bank("BENCH_r03.json", 3, {
        "metric": "nakamoto_selfish_mining_env_steps_per_sec_per_chip",
        "backend": "tpu", "value": 305_000_000,
        "unit": "env-steps/sec/chip"})
    bank("BENCH_r09.json", 9, {
        "metric": "nakamoto_selfish_mining_env_steps_per_sec_per_chip",
        "backend": "tpu", "value": 17, "unit": "env-steps/sec/chip",
        "outage": True, "fallback_reason": "wedged backend"})
    best = bench._last_known_tpu("nakamoto_selfish_mining",
                                 root=str(tmp_path))
    assert best is not None
    assert best["round"] == 3 and best["value"] == 305_000_000
    # error rows are just as ineligible
    bank("BENCH_r10.json", 10, {
        "metric": "nakamoto_selfish_mining_env_steps_per_sec_per_chip",
        "backend": "tpu", "error": "guard failed"})
    best = bench._last_known_tpu("nakamoto_selfish_mining",
                                 root=str(tmp_path))
    assert best["round"] == 3
    # all-poisoned bank: no baseline rather than a poisoned one
    assert bench._last_known_tpu("nakamoto_selfish_mining",
                                 root=str(tmp_path / "empty")) is None


def test_final_rung_hang_does_not_wedge_remaining_configs(monkeypatch):
    """PR-8 regression (the bench hang asymmetry): the old one-strike
    `wedged` flag wrote the TPU off for EVERY remaining config after a
    final-rung hang.  Now the hung config takes its CPU fallback and
    each later config still gets a supervised TPU attempt — whose own
    probe-before-run is what decides device health."""
    import json

    from cpr_tpu import supervisor as sup

    sites, cpu_children = [], []

    def fake_supervise(cmd, *, site, config=None, env=None, cwd=None,
                       guard_rc=None, require_json=True, on_retry=None,
                       classify=None):
        sites.append(site)
        name = site.split(":", 1)[1]
        if name == "bk8_withholding":  # first config: single-rung ladder
            raise sup.SupervisedHang(f"{site}: hung past 5s wall budget")
        row = {"metric": f"{name}_env_steps_per_sec_per_chip",
               "backend": "tpu", "value": 1000.0,
               "unit": "env-steps/sec/chip"}
        return sup.Outcome(json.dumps(row), 0, 1, 0.1)

    def fake_run_child(cmd, *, wall_timeout_s, quiet_s=None, **kw):
        name = cmd[cmd.index("--direct-one") + 1]
        cpu_children.append(name)
        row = {"metric": f"{name}_env_steps_per_sec_per_chip",
               "backend": "cpu", "value": 10.0,
               "unit": "env-steps/sec/chip"}
        line = json.dumps(row)
        return sup.Attempt("ok", 0, [line], line, "", 0.1, False, 0, None)

    written = {}
    monkeypatch.setattr(bench.supervisor, "supervise", fake_supervise)
    monkeypatch.setattr(bench.supervisor, "run_child", fake_run_child)
    monkeypatch.setattr(bench, "_bank_and_gate", lambda row: None)
    monkeypatch.setattr(bench, "_write_configs_json",
                        lambda rows: written.setdefault("rows", rows))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    bench.run_configs_isolated(5.0)

    # every config earned a TPU attempt despite the first one hanging
    assert sites == [f"bench:{n}" for n in bench.CONFIGS]
    assert cpu_children == ["bk8_withholding"]  # fallback for it alone
    rows = written["rows"]
    assert len(rows) == len(bench.CONFIGS)
    assert rows[0]["backend"] == "cpu" and rows[0]["outage"] is True
    assert "hung past watchdog" in rows[0]["fallback_reason"]
    assert all(r["backend"] == "tpu" for r in rows[1:])
    # the hang stamped a fault timestamp, so the later on-chip rows
    # carry recovery-window context instead of claiming a quiet worker
    assert all("secs_since_worker_fault" in r for r in rows[1:])


def test_chunked_episode_stats_matches_unchunked():
    """The chunked stats driver (the axon per-call-ceiling workaround,
    JaxEnv.make_episode_stats_fn) must produce the same per-env stats
    as one vmapped episode_stats call, up to float summation order."""
    import jax
    import numpy as np

    from cpr_tpu.envs.ethereum import EthereumSSZ
    from cpr_tpu.params import make_params

    env = EthereumSSZ("byzantium", max_steps_hint=48)
    params = make_params(alpha=0.35, gamma=0.5, max_steps=40)
    pol = env.policies["fn19"]
    keys = jax.random.split(jax.random.PRNGKey(7), 16)
    whole = env.make_episode_stats_fn(params, pol, 96)(keys)
    # chunk boundary NOT dividing n_steps exercises the remainder call
    parts = env.make_episode_stats_fn(params, pol, 96, chunk=40)(keys)
    assert set(whole) == set(parts)
    for k in whole:
        np.testing.assert_allclose(np.asarray(whole[k]),
                                   np.asarray(parts[k]), rtol=1e-5,
                                   err_msg=k)
