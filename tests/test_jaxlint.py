"""jaxlint: per-rule fixture tests, CLI contract, and the repo gate.

The fixture tree (tests/fixtures/jaxlint/) is a miniature repo linted
with its own root, so path-scoped rules (wall-clock's cpr_tpu/ scope,
raw-write's resilience exemption, donate-carry's hot-path list,
event-schema's cross-module EVENT_FIELDS resolution) see realistic
repo-relative paths.  The repo gate at the bottom is the tier-1
enforcement point: every future PR inherits it.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from cpr_tpu.analysis import run_lint, rule_ids
from cpr_tpu.analysis.core import LintContext, load_baseline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXROOT = os.path.join(REPO, "tests", "fixtures", "jaxlint")
CLI = os.path.join(REPO, "tools", "jaxlint.py")

# rule id -> fixture stem (relative to FIXROOT); <stem>_bad.py seeds
# violations, <stem>_ok.py exercises the sanctioned idioms
CASES = {
    "wall-clock": "cpr_tpu/wall_clock",
    "raw-write": "cpr_tpu/raw_write",
    "event-schema": "cpr_tpu/event_schema",
    "jit-in-loop": "cpr_tpu/jit_in_loop",
    "donate-carry": "cpr_tpu/parallel/donate",
    "key-reuse": "cpr_tpu/key_reuse",
    "host-sync": "cpr_tpu/host_sync",
}


def test_every_rule_has_fixtures():
    assert set(CASES) == set(rule_ids())
    for stem in CASES.values():
        for suffix in ("_bad.py", "_ok.py"):
            assert os.path.exists(os.path.join(FIXROOT, stem + suffix))


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_catches_seeded_violation(rule):
    path = os.path.join(FIXROOT, CASES[rule] + "_bad.py")
    found = run_lint([path], root=FIXROOT)
    assert found, f"{rule} missed its seeded violation"
    # only the rule under test fires: bad fixtures must not leak
    # cross-rule noise, or the parametrization stops meaning anything
    assert {f.rule for f in found} == {rule}
    assert all(f.path == CASES[rule] + "_bad.py" for f in found)
    assert all(f.line > 0 and f.message for f in found)


@pytest.mark.parametrize("rule", sorted(CASES))
def test_rule_clean_on_sanctioned_idioms(rule):
    path = os.path.join(FIXROOT, CASES[rule] + "_ok.py")
    assert run_lint([path], root=FIXROOT) == []


def test_raw_write_exempts_resilience():
    path = os.path.join(FIXROOT, "cpr_tpu", "resilience.py")
    assert run_lint([path], root=FIXROOT) == []


def test_event_fields_resolved_cross_module_by_ast():
    schema = LintContext(root=FIXROOT).event_fields()
    assert schema == {
        "compile": ("fn", "compile_s"),
        "retry": ("attempt", "delay_s", "error"),
        "request": ("trace_id", "op", "status", "total_s"),
        "admission": ("reason", "op", "priority", "tenant",
                      "retry_after_s"),
        "route": ("action", "replica", "op"),
        "attack_sweep": ("protocol", "topology", "lanes", "policies",
                         "drops"),
        "mdp_compile": ("protocol", "cutoff", "rounds", "states",
                        "transitions", "n_workers"),
        "alert": ("signal", "severity", "window_s", "value", "budget",
                  "burn_rate"),
        "perf_gate": ("metric", "backend", "verdict", "value",
                      "baseline", "run", "baseline_runs"),
        "memory": ("scope", "peak_bytes", "source"),
        "integrity": ("artifact", "artifact_kind", "reason",
                      "action"),
        "learn": ("role", "steps", "batches", "fingerprint",
                  "staleness_s"),
    }


def test_disable_rule_and_unknown_rule():
    bad = os.path.join(FIXROOT, CASES["raw-write"] + "_bad.py")
    assert run_lint([bad], root=FIXROOT, disable=["raw-write"]) == []
    with pytest.raises(ValueError, match="unknown rule"):
        run_lint([bad], root=FIXROOT, disable=["no-such-rule"])


def test_baseline_grandfathers_existing_findings(tmp_path):
    bad = os.path.join(FIXROOT, CASES["key-reuse"] + "_bad.py")
    found = run_lint([bad], root=FIXROOT)
    assert found
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(
        {"findings": [f.as_dict() for f in found]}))
    assert run_lint([bad], root=FIXROOT,
                    baseline=load_baseline(str(bl))) == []


# -- CLI contract ------------------------------------------------------------


def _cli(*args, env=None):
    e = dict(os.environ)
    if env:
        e.update(env)
    return subprocess.run([sys.executable, CLI, *args], cwd=REPO,
                          capture_output=True, text=True, env=e)


def test_cli_json_exit_codes_disable_and_baseline(tmp_path):
    bad = "tests/fixtures/jaxlint/cpr_tpu/raw_write_bad.py"
    r = _cli(bad, "--format", "json")
    assert r.returncode == 1
    report = json.loads(r.stdout)
    assert report["tool"] == "jaxlint"
    assert {x["id"] for x in report["rules"]} == set(rule_ids())
    assert report["findings"]
    assert all(f["rule"] == "raw-write" for f in report["findings"])

    assert _cli(bad, "--disable", "raw-write").returncode == 0
    assert _cli(bad, "--disable", "bogus").returncode == 2

    bl = str(tmp_path / "bl.json")
    assert _cli(bad, "--write-baseline", bl).returncode == 0
    assert _cli(bad, "--baseline", bl).returncode == 0

    out = str(tmp_path / "report.json")
    r = _cli(bad, "--output", out)
    assert r.returncode == 1
    assert json.loads(open(out).read())["findings"]


def test_cli_lints_repo_without_importing_jax(tmp_path):
    # a poisoned jax on PYTHONPATH turns any jax import into a crash;
    # the CLI must stay pure-AST (and fast) over the whole repo
    (tmp_path / "jax.py").write_text(
        "raise ImportError('jaxlint must not import jax')\n")
    t0 = time.perf_counter()
    r = _cli("cpr_tpu", "tools", env={"PYTHONPATH": str(tmp_path)})
    dt = time.perf_counter() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    assert dt < 5.0, f"linter took {dt:.1f}s (budget 5s)"


# -- the tier-1 gate ---------------------------------------------------------


def test_repo_is_lint_clean():
    """The gate every future PR inherits: cpr_tpu/ + tools/ lint clean
    (inline disables must carry reasons; there is no baseline debt).
    This also owns the PR-2 no-wall-clock invariant, which used to be a
    bespoke tokenize sweep in test_observability.py."""
    found = run_lint(["cpr_tpu", "tools"], root=REPO)
    assert found == [], "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in found)
