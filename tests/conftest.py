"""Test harness: run everything on a virtual 8-device CPU mesh so that
multi-chip sharding is exercised without TPU hardware (the driver
separately dry-runs the multi-chip path).

Note: this environment's sitecustomize registers the axon TPU plugin and
forces jax_platforms="axon,cpu", so the JAX_PLATFORMS env var alone is NOT
enough — the programmatic config update below is what actually selects the
CPU backend."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
