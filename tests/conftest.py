"""Test harness: run everything on a virtual 8-device CPU mesh so that
multi-chip sharding is exercised without TPU hardware (the driver
separately dry-runs the multi-chip path).

Note: this environment's sitecustomize registers the axon TPU plugin and
forces jax_platforms="axon,cpu", so the JAX_PLATFORMS env var alone is NOT
enough — the programmatic config update below is what actually selects the
CPU backend."""

import os

import pytest

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if ("xla_backend_optimization_level" not in flags
        and not os.environ.get("CPR_TEST_FULL_OPT")):
    # compile time dominates the suite (the big DAG-env kernels take
    # 15-40s each to build); at test shapes the runtime difference is
    # noise, so trade codegen quality for ~2x faster compiles.  Set
    # CPR_TEST_FULL_OPT=1 to test with production codegen.
    flags = (flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

if os.environ.get("CPR_JAX_CACHE"):
    # opt-in persistent compile cache (reruns start warm).  Not default:
    # the XLA:CPU AOT loader logs machine-feature-mismatch noise on
    # load, and a stale cache across toolchain bumps risks SIGILL.
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["CPR_JAX_CACHE"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


# -- tiering ---------------------------------------------------------------
# The deep stochastic batteries (full DAG-env policy sweeps) compile
# multi-hundred-line jitted kernels many times; on the CPU host they push
# the suite far past a CI budget.  Default runs execute the fast tier
# (every module still has smoke/contract coverage via
# test_protocol_smoke.py); the slow tier runs with --runslow or
# CPR_RUN_SLOW=1, in a single process (see the cache-release hook at
# the bottom of this file).


def pytest_addoption(parser):
    parser.addoption("--runslow", action="store_true", default=False,
                     help="run tests marked slow (deep stochastic tier)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: deep stochastic battery, opt-in via --runslow")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow") or \
            os.environ.get("CPR_RUN_SLOW", "").lower() in ("1", "true",
                                                           "yes"):
        return
    skip = pytest.mark.skip(reason="slow tier: use --runslow or CPR_RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


# -- single-process slow tier ------------------------------------------------
# One process compiling the whole slow tier's worth of kernels used to
# segfault XLA:CPU's JIT deterministically ~200 compilations in; the
# cause is accumulated LIVE executables, not a compile counter —
# releasing them (jax.clear_caches + dropping the env-registry memo
# that pins jitted methods) at the old two-process boundary lets one
# process run everything (verified 2026-07: 216 passed, 44m, vs 49m
# for the split).  Boundary overridable via CPR_CLEAR_CACHES_AT
# (comma-separated module basenames; "none" disables).

_DEFAULT_CLEAR_AT = "test_registry.py"
_cleared_at: set = set()


def pytest_runtest_setup(item):
    if not (item.config.getoption("--runslow")
            or os.environ.get("CPR_RUN_SLOW", "").lower()
            in ("1", "true", "yes")):
        return  # fast tier sits far from the ceiling; skip the rebuilds
    boundary = os.environ.get("CPR_CLEAR_CACHES_AT", _DEFAULT_CLEAR_AT)
    if boundary == "none":
        return
    base = os.path.basename(str(item.fspath))
    if base in boundary.split(",") and base not in _cleared_at:
        _cleared_at.add(base)
        import gc

        from cpr_tpu.envs import registry

        registry.clear_memo()  # drop env instances holding jit caches
        jax.clear_caches()
        gc.collect()
