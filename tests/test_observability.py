"""Observability tests: DAG exports, causal traces, malformed-DAG dumps,
the difficulty-adjustment convergence loop, and the runtime telemetry
layer (spans, manifests, bench outage tagging).

Reference counterparts: log.ml GraphLogger export, dagtools.ml dot/
GraphML serializers and Exn dump hook, and gym/ocaml/test/test_daa.py.
The telemetry half has no reference counterpart — it exists because
async dispatch and chip outages are TPU-runtime problems the event-loop
simulator never had.
"""

import collections
import importlib.util
import json
import os
import re
import sys
import time
from xml.etree import ElementTree as ET

import jax
import numpy as np
import pytest

from cpr_tpu import telemetry, trace
from cpr_tpu.native import OracleSim
from cpr_tpu.params import make_params

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def test_env_state_dag_export():
    from cpr_tpu.envs.bk import BkSSZ

    env = BkSSZ(k=4, max_steps_hint=48)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=32)
    state, obs = jax.jit(env.reset)(jax.random.PRNGKey(0), params)
    step = jax.jit(env.step)
    for _ in range(20):
        state, obs, r, d, i = step(state, env.policies["honest"](obs),
                                   params)
    view = trace.view_of_env_state(state.dag)
    assert len(view.nodes) > 1
    assert all(c > p for c, p in view.edges), "ids are topological"
    dot = trace.to_dot(view)
    assert dot.startswith("digraph") and "->" in dot
    xml = trace.to_graphml(view)
    root = ET.fromstring(xml)  # well-formed
    assert root.tag.endswith("graphml")


def test_oracle_causal_trace_export():
    s = OracleSim("nakamoto", topology="clique", n_nodes=4,
                  activation_delay=10.0, propagation_delay=1.0, seed=1)
    s.run(50)
    view = trace.view_of_oracle(s)
    assert len(view.nodes) == int(s.metric("n_blocks")) + 1
    kinds = collections.Counter(k for _, k, _, _ in view.events)
    assert kinds["appends"] == 50  # one append per activation
    assert kinds["shares"] == 50  # honest nodes share every block
    assert kinds["learns"] >= kinds["appends"]  # deliveries to others
    # events are time-ordered
    times = [t for t, *_ in view.events]
    assert times == sorted(times)
    xml = trace.to_graphml(view)
    root = ET.fromstring(xml)
    ids = {n.get("id") for n in root.iter() if n.tag.endswith("node")}
    assert any(i.startswith("event") for i in ids)
    assert any(i.startswith("vertex") for i in ids)


def test_malformed_dag_dump(tmp_path, monkeypatch):
    target = tmp_path / "malformed.dot"
    monkeypatch.setenv(trace.MALFORMED_ENV_VAR, str(target))
    view = trace.DagView(nodes=[{"id": 0}, {"id": 1}], edges=[(1, 0)])
    with pytest.raises(trace.MalformedDag, match="dumped to"):
        trace.raise_malformed(view, "test failure")
    assert target.exists() and "digraph" in target.read_text()


def test_daa_convergence():
    """The reference DAA feedback test (test_daa.py:7-58): selfish mining
    inflates the block interval; the difficulty-adjustment loop feeding
    observed chain-time/progress back into activation_delay restores the
    target interval."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ

    env = NakamotoSSZ()
    target, eps = 600.0, 25.0
    policy = env.policies["sapirshtein-2016-sm1"]
    # one compile for the whole feedback loop: activation_delay flows in
    # through params
    fn = jax.jit(jax.vmap(
        lambda k, p: env.episode_stats(k, p, policy, 110),
        in_axes=(0, None)))

    def measure(activation_delay, seed):
        params = make_params(alpha=1 / 3, gamma=0.5, max_steps=100,
                             activation_delay=activation_delay)
        keys = jax.random.split(jax.random.PRNGKey(seed), 64)
        stats = jax.block_until_ready(fn(keys, params))
        return (float(np.asarray(stats["episode_chain_time"]).mean()),
                float(np.asarray(stats["episode_progress"]).mean()))

    ct, pr = measure(target, 0)
    assert not (target - eps < ct / pr < target + eps), \
        "selfish mining must push the interval out of tolerance"

    ad = collections.deque([target], maxlen=20)
    cts = collections.deque([ct], maxlen=20)
    prs = collections.deque([pr], maxlen=20)
    for i in range(12):
        next_ad = target * float(np.mean(
            np.array(ad) / np.array(cts) * np.array(prs)))
        ad.append(next_ad)
        ct, pr = measure(next_ad, i + 1)
        cts.append(ct)
        prs.append(pr)
    observed = float(np.sum(cts) / np.sum(prs))
    assert target - eps < observed < target + eps, observed


# -- runtime telemetry (cpr_tpu/telemetry.py) --------------------------------


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_span_events_parse_and_schema_complete(tmp_path):
    """Every span event is one JSON line carrying all SPAN_KEYS, with
    correct nesting (path/depth) and contained, monotonic timestamps."""
    path = tmp_path / "tele.jsonl"
    tele = telemetry.Telemetry(str(path))
    with tele.span("outer", env_steps=100) as outer:
        with tele.span("inner"):
            pass
    tele.event("marker", detail=1)
    tele.close()

    events = _events(path)  # every line parses
    spans = [e for e in events if e["kind"] == "span"]
    assert len(spans) == 2
    for e in spans:
        assert all(k in e for k in telemetry.SPAN_KEYS), e
    inner, outer_ev = spans  # inner exits (and emits) first
    assert inner["name"] == "inner"
    assert inner["path"] == "outer/inner" and inner["depth"] == 1
    assert outer_ev["path"] == "outer" and outer_ev["depth"] == 0
    # the child's interval nests inside the parent's, all monotonic
    assert (outer_ev["t_start"] <= inner["t_start"] <= inner["t_end"]
            <= outer_ev["t_end"])
    assert outer_ev["dur_s"] == pytest.approx(
        outer_ev["t_end"] - outer_ev["t_start"])
    # counters surface as derived rates
    assert outer_ev["counters"] == {"env_steps": 100}
    assert outer_ev["per_sec"]["env_steps"] == pytest.approx(
        100 / outer_ev["dur_s"])
    assert outer.dur_s == outer_ev["dur_s"]
    marker = [e for e in events if e["kind"] == "event"]
    assert marker and marker[0]["name"] == "marker"


def test_span_records_error_and_unwinds_stack(tmp_path):
    path = tmp_path / "tele.jsonl"
    tele = telemetry.Telemetry(str(path))
    with pytest.raises(ValueError):
        with tele.span("boom"):
            raise ValueError("kaput")
    tele.close()
    (ev,) = _events(path)
    assert ev["error"] == "ValueError: kaput"
    assert tele._stack == []  # the failed span did not leak nesting


def test_manifest_backend_devices_git_sha():
    man = telemetry.run_manifest(config={"n_envs": 4})
    assert man["kind"] == "manifest"
    assert man["schema"] == telemetry.SCHEMA_VERSION
    assert man["backend"] == "cpu"  # conftest forces the CPU mesh
    assert man["device_count"] == len(jax.devices())
    assert man["device_kind"] and man["jax_version"]
    assert re.fullmatch(r"[0-9a-f]{40}", man["git_sha"])
    assert man["config"] == {"n_envs": 4}


def test_span_fences_async_dispatch():
    """Device work still in flight at span exit must land INSIDE the
    span.  jax.block_until_ready blocks on any leaf exposing
    block_until_ready(), so a leaf that 'completes' ~50ms late is a
    deterministic stand-in for async dispatch: a fenced span absorbs
    the wait, an unfenced one exits immediately."""

    class SlowLeaf:
        def block_until_ready(self):
            time.sleep(0.05)
            return self

    tele = telemetry.Telemetry()  # disabled sink; spans still time
    with tele.span("fenced") as sp:
        out = sp.fence({"stats": SlowLeaf()})
    assert isinstance(out["stats"], SlowLeaf)  # passthrough
    assert sp.dur_s >= 0.05
    with tele.span("unfenced") as sp:
        SlowLeaf()
    assert sp.dur_s < 0.05


def test_current_reads_env_var(tmp_path, monkeypatch):
    path = tmp_path / "env.jsonl"
    monkeypatch.setattr(telemetry, "_default", None)
    monkeypatch.setenv(telemetry.TELEMETRY_ENV_VAR, str(path))
    try:
        tele = telemetry.current()
        assert tele.enabled
        with tele.span("s"):
            pass
        assert _events(path)[0]["name"] == "s"
    finally:
        telemetry.configure(None)  # don't leak a sink into other tests


def test_bench_fallback_rows_carry_outage_fields():
    """VERDICT weak #1: a CPU-fallback row must say it IS a fallback
    and what the chip last measured, so a 306x 'regression' reads as an
    outage.  The banked BENCH_r*.json artifacts in the repo root are
    the fixture."""
    import bench

    fields = bench._outage_fields("tpu watchdog timeout after 360s",
                                  "nakamoto_selfish_mining")
    assert fields["outage"] is True
    assert "watchdog" in fields["fallback_reason"]
    last = fields["last_known_tpu"]
    assert last is not None, "banked TPU rows exist for the headline"
    assert last["value"] > 0 and last["unit"]
    assert re.match(r"BENCH.*\.json", last["source"])
    assert last["round"] >= 4  # r04 banked the first headline TPU row
    # a metric never measured on chip degrades to an honest null
    none = bench._outage_fields("boom", "no_such_metric_prefix")
    assert none["outage"] is True and none["last_known_tpu"] is None


def test_bench_fallback_emits_tpu_outage_event(tmp_path):
    """The same fallback that tags the row also marks the telemetry
    stream with a schema-v2 `tpu_outage` point event, so a trace read
    long after the run still explains the backend switch."""
    import bench

    path = tmp_path / "outage.jsonl"
    telemetry.configure(str(path))
    try:
        bench._outage_fields("tpu watchdog timeout after 360s",
                             "nakamoto_selfish_mining")
    finally:
        telemetry.configure(None)
    (ev,) = [e for e in _events(path) if e.get("kind") == "event"]
    assert ev["name"] == "tpu_outage"
    assert "watchdog" in ev["reason"]
    assert ev["metric_prefix"] == "nakamoto_selfish_mining"
    missing = [k for k in telemetry.EVENT_FIELDS["tpu_outage"]
               if k not in ev]
    assert not missing


# the no-wall-clock-interval-timing invariant is now owned by the
# jaxlint wall-clock rule (cpr_tpu/analysis/rules.py), enforced by
# tests/test_jaxlint.py::test_repo_is_lint_clean


def _load_trace_summary():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_summary.py")
    spec = importlib.util.spec_from_file_location("trace_summary", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_summary_validate(tmp_path, capsys):
    """The artifact validator behind `make telemetry-smoke`: a stream
    written by the telemetry layer passes; truncated spans and
    manifest-less streams fail with a nonzero exit."""
    ts = _load_trace_summary()
    good = tmp_path / "good.jsonl"
    tele = telemetry.Telemetry(str(good))
    with tele.span("compile"):
        pass
    with tele.span("measure", env_steps=64):
        pass
    tele.manifest(config={"metric": "nakamoto_sm1"})
    tele.close()
    events, bad = ts.read_events(str(good))
    assert ts.validate(events, bad) == []
    ts.main(["trace_summary", str(good), "--validate"])  # exits 0
    out = capsys.readouterr().out
    assert "compile" in out and "env_steps" in out

    lame = tmp_path / "lame.jsonl"
    lame.write_text(json.dumps({"kind": "span", "name": "x"}) + "\n"
                    "not json\n")
    events, bad = ts.read_events(str(lame))
    errors = ts.validate(events, bad)
    assert any("missing" in e for e in errors)
    assert any("not JSON" in e for e in errors)
    assert any("manifest" in e for e in errors)
    with pytest.raises(SystemExit) as exc:
        ts.main(["trace_summary", str(lame), "--validate"])
    assert exc.value.code == 1


def test_trace_summary_validate_v4_netsim_event(tmp_path, capsys):
    """The v4 schema's netsim event (PR 5) round-trips the validator: a
    fully-typed event passes, including under `--expect netsim`, and
    dropping a declared field is caught.  (The pre-v4 validation tests
    above never exercise an event newer than v3.)"""
    ts = _load_trace_summary()
    good = tmp_path / "netsim.jsonl"
    tele = telemetry.Telemetry(str(good))
    with tele.span("netsim_run"):
        pass
    tele.event("netsim", protocol="nakamoto", lanes=8,
               activations=1024, steps=4096, drops=0)
    tele.manifest(config={"metric": "netsim_nakamoto"})
    tele.close()
    events, bad = ts.read_events(str(good))
    assert any(e.get("name") == "netsim" for e in events)
    (man,) = [e for e in events if e.get("kind") == "manifest"]
    assert man["schema"] >= 4
    assert ts.validate(events, bad) == []
    assert ts.validate(events, bad, expect=("netsim",)) == []
    ts.main(["trace_summary", str(good), "--validate",
             "--expect", "netsim"])  # exits 0
    capsys.readouterr()

    lame = tmp_path / "lame.jsonl"
    lines = []
    for line in good.read_text().splitlines():
        e = json.loads(line)
        if e.get("name") == "netsim":
            e.pop("drops")
        lines.append(json.dumps(e))
    lame.write_text("\n".join(lines) + "\n")
    events, bad = ts.read_events(str(lame))
    errors = ts.validate(events, bad)
    assert any("netsim" in err and "drops" in err for err in errors)


def test_trace_summary_validate_v8_request_event(tmp_path, capsys):
    """The v8 schema's request event (PR 10) round-trips the
    validator: a fully-typed event passes, including under
    `--expect request`, and dropping a declared latency field is
    caught."""
    ts = _load_trace_summary()
    good = tmp_path / "request.jsonl"
    tele = telemetry.Telemetry(str(good))
    with tele.span("serve"):
        pass
    tele.event("request", trace_id="ab12cd34", op="episode.run",
               status="ok", queue_wait_s=0.1, service_s=0.3,
               total_s=0.4, role="server", run="r1", session=1,
               lane=0, splice_s=0.01)
    tele.manifest(config={"entry": "serve"})
    tele.close()
    events, bad = ts.read_events(str(good))
    (man,) = [e for e in events if e.get("kind") == "manifest"]
    assert man["schema"] >= 8 and man["run"]
    assert ts.validate(events, bad) == []
    assert ts.validate(events, bad, expect=("request",)) == []
    ts.main(["trace_summary", str(good), "--validate",
             "--expect", "request"])  # exits 0
    out = capsys.readouterr().out
    assert "episode.run" in out and "server" in out

    lame = tmp_path / "lame.jsonl"
    lines = []
    for line in good.read_text().splitlines():
        e = json.loads(line)
        if e.get("name") == "request":
            e.pop("total_s")
        lines.append(json.dumps(e))
    lame.write_text("\n".join(lines) + "\n")
    events, bad = ts.read_events(str(lame))
    errors = ts.validate(events, bad)
    assert any("request" in err and "total_s" in err for err in errors)


def _load_trace_stitch():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_stitch.py")
    spec = importlib.util.spec_from_file_location("trace_stitch", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _request_line(tele, trace_id, role, run, op="episode.run",
                  status="ok", queue_wait_s=0.1, service_s=0.3,
                  total_s=0.4, **extra):
    tele.event("request", trace_id=trace_id, op=op, status=status,
               queue_wait_s=queue_wait_s, service_s=service_s,
               total_s=total_s, role=role, run=run, **extra)


def test_trace_stitch_merges_streams_and_tolerates_orphans(tmp_path,
                                                           capsys):
    """Satellite d: three streams of one run — the serve server (a
    supervisor child), the supervising parent, and a client — merge
    into one trace tree keyed by the shared run id; a trace_id seen on
    only one side of the wire is kept and marked, never dropped."""
    stitcher = _load_trace_stitch()
    run = "deadbeef00112233"
    server = tmp_path / "server.jsonl"
    tele = telemetry.Telemetry(str(server))
    tele.emit({"kind": "manifest", "run": run, "backend": "cpu"})
    _request_line(tele, "t1", "server", run, splice_s=0.02, lane=0,
                  queue_wait_s=0.1, service_s=0.3, total_s=0.4)
    _request_line(tele, "t-server-only", "server", run, op="stats",
                  queue_wait_s=0.0, service_s=0.001, total_s=0.001)
    tele.close()
    parent = tmp_path / "parent.jsonl"
    tele = telemetry.Telemetry(str(parent))
    tele.emit({"kind": "manifest", "run": run, "backend": "cpu"})
    tele.event("supervisor", action="probe", site="serve",
               reason="startup")
    tele.close()
    client = tmp_path / "client.jsonl"
    tele = telemetry.Telemetry(str(client))
    tele.emit({"kind": "manifest", "run": run})
    _request_line(tele, "t1", "client", run, total_s=0.45)
    _request_line(tele, "t-client-only", "client", run, total_s=0.2)
    tele.close()

    st = stitcher.stitch([str(server), str(parent), str(client)])
    assert set(st["runs"]) == {run}
    assert sorted(st["runs"][run]) == ["client.jsonl", "parent.jsonl",
                                       "server.jsonl"]
    by_id = {t["trace_id"]: t for t in st["traces"]}
    assert len(by_id) == 3 and st["orphans"] == 2
    t1 = by_id["t1"]
    assert t1["orphan"] is None and t1["run"] == run
    bd = t1["breakdown"]
    assert bd["splice_s"] == pytest.approx(0.02)
    assert bd["queue_s"] == pytest.approx(0.08)  # wait minus splice
    assert bd["burst_s"] == pytest.approx(0.3)
    assert bd["reply_s"] == pytest.approx(0.05)  # client - server
    assert bd["total_s"] == pytest.approx(0.45)  # the client's wall
    assert by_id["t-server-only"]["orphan"] == "no-client"
    assert by_id["t-client-only"]["orphan"] == "no-server"
    # one-sided traces keep a partial breakdown instead of exploding
    lonely = by_id["t-client-only"]["breakdown"]
    assert lonely["burst_s"] is None and lonely["reply_s"] is None
    assert lonely["total_s"] == pytest.approx(0.2)
    assert st["ops"]["episode.run"]["two_sided"] == 1

    rc = stitcher.main([str(server), str(parent), str(client)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "t-client-only" in out and "no-server" in out
    assert f"run {run}" in out


def test_trace_stitch_router_hop_segment(tmp_path, capsys):
    """Satellite 4: a role="router" stream is an optional third side —
    a three-sided trace gains the route leg (router total minus server
    total) and the reply leg is measured against the router's wall,
    while traces without a router event keep the exact two-sided
    breakdown (single-process streams stitch unchanged)."""
    stitcher = _load_trace_stitch()
    run = "feedface00112233"
    server = tmp_path / "server.jsonl"
    tele = telemetry.Telemetry(str(server))
    tele.emit({"kind": "manifest", "run": run, "backend": "cpu"})
    _request_line(tele, "t1", "server", run, splice_s=0.02,
                  queue_wait_s=0.1, service_s=0.3, total_s=0.4)
    _request_line(tele, "t2", "server", run, total_s=0.4)
    tele.close()
    router = tmp_path / "router.jsonl"
    tele = telemetry.Telemetry(str(router))
    tele.emit({"kind": "manifest", "run": run, "backend": "cpu"})
    _request_line(tele, "t1", "router", run, total_s=0.5)
    # a router-only trace (its server stream was cut mid-run)
    _request_line(tele, "t-router-only", "router", run, total_s=0.1)
    tele.close()
    client = tmp_path / "client.jsonl"
    tele = telemetry.Telemetry(str(client))
    tele.emit({"kind": "manifest", "run": run})
    _request_line(tele, "t1", "client", run, total_s=0.56)
    _request_line(tele, "t2", "client", run, total_s=0.45)
    tele.close()

    st = stitcher.stitch([str(server), str(router), str(client)])
    by_id = {t["trace_id"]: t for t in st["traces"]}
    t1 = by_id["t1"]
    assert t1["orphan"] is None
    bd = t1["breakdown"]
    assert bd["route_s"] == pytest.approx(0.1)  # router - server
    assert bd["queue_s"] == pytest.approx(0.08)
    assert bd["burst_s"] == pytest.approx(0.3)
    # the reply leg is past the router, the furthest-upstream total
    assert bd["reply_s"] == pytest.approx(0.06)
    assert bd["total_s"] == pytest.approx(0.56)
    # routerless trace on the same streams: exact two-sided breakdown
    bd2 = by_id["t2"]["breakdown"]
    assert bd2["route_s"] is None
    assert bd2["reply_s"] == pytest.approx(0.05)
    # router-only = orphan (no server side to split against)
    assert by_id["t-router-only"]["orphan"] == "no-client"
    assert by_id["t-router-only"]["breakdown"]["route_s"] is None

    assert stitcher.main([str(server), str(router), str(client)]) == 0
    out = capsys.readouterr().out
    assert "route" in out


def test_trace_stitch_empty_streams_exit_nonzero(tmp_path, capsys):
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"kind": "manifest", "run": "r"}) + "\n")
    stitcher = _load_trace_stitch()
    assert stitcher.main([str(empty)]) == 1
    capsys.readouterr()


def test_malformed_dag_dump_atomic(tmp_path, monkeypatch):
    """The forensics dump rides the resilience atomic writer: the
    final name holds the complete dot text and no orphaned tmp
    sibling survives."""
    target = tmp_path / "malformed.dot"
    monkeypatch.setenv(trace.MALFORMED_ENV_VAR, str(target))
    view = trace.DagView(nodes=[{"id": 0}, {"id": 1}], edges=[(1, 0)])
    with pytest.raises(trace.MalformedDag, match="dumped to"):
        trace.raise_malformed(view, "parent id above child")
    assert target.read_text().startswith("digraph")
    assert [p.name for p in tmp_path.iterdir()] == ["malformed.dot"]


def test_trace_summary_v14_alert_table_and_expect(tmp_path, capsys):
    """Satellite a: the v14 `alert` event round-trips the validator
    (including `--expect alert`), renders as the aggregated alert
    table, and a burn_rate-less alert is caught as a schema error."""
    ts = _load_trace_summary()
    good = tmp_path / "alerts.jsonl"
    tele = telemetry.Telemetry(str(good))
    with tele.span("serve"):
        pass
    for burn in (8.0, 20.0):
        tele.event("alert", signal="shed_rate", severity="page",
                   window_s=5.0, value=burn * 0.02, budget=0.02,
                   burn_rate=burn, cls=None, threshold=4.0, slo_s=0.5)
    tele.event("alert", signal="p99_over_slo", severity="ticket",
               window_s=30.0, value=1.2, budget=0.5, burn_rate=2.4,
               cls="interactive", threshold=1.0, slo_s=0.5)
    tele.manifest(config={"entry": "serve"})
    tele.close()
    events, bad = ts.read_events(str(good))
    (man,) = [e for e in events if e.get("kind") == "manifest"]
    assert man["schema"] >= 14
    assert ts.validate(events, bad) == []
    assert ts.validate(events, bad, expect=("alert",)) == []
    ts.main(["trace_summary", str(good), "--validate",
             "--expect", "alert"])  # exits 0
    out = capsys.readouterr().out
    # the aggregate table: one line per signal x class x severity x
    # window, carrying the fire count and the worst burn
    assert "alert signal" in out and "max_burn" in out
    (shed_line,) = [ln for ln in out.splitlines()
                    if ln.startswith("shed_rate")]
    assert " 2 " in shed_line and "20.0" in shed_line
    assert any(ln.startswith("p99_over_slo") and "interactive" in ln
               for ln in out.splitlines())
    # an alert stream without any alert events fails the expectation
    assert any("alert" in err for err in
               ts.validate([man], [], expect=("alert",)))

    lame = tmp_path / "lame.jsonl"
    lines = []
    for line in good.read_text().splitlines():
        e = json.loads(line)
        if e.get("name") == "alert":
            e.pop("burn_rate")
        lines.append(json.dumps(e))
    lame.write_text("\n".join(lines) + "\n")
    events, bad = ts.read_events(str(lame))
    errors = ts.validate(events, bad)
    assert any("alert" in err and "burn_rate" in err for err in errors)


def test_trace_stitch_tallies_unpaired_typed_events(tmp_path, capsys):
    """Satellite a: typed point events with no trace side (v14 alerts,
    route decisions, admission sheds) are tolerated and tallied per
    name — a stream full of alerts reads as health signal, not as
    stitching loss, and the request pairing is unaffected."""
    stitcher = _load_trace_stitch()
    run = "cafebabe00112233"
    server = tmp_path / "server.jsonl"
    tele = telemetry.Telemetry(str(server))
    tele.emit({"kind": "manifest", "run": run, "backend": "cpu"})
    _request_line(tele, "t1", "server", run)
    for burn in (8.0, 16.0):
        tele.event("alert", signal="shed_rate", severity="page",
                   window_s=5.0, value=burn * 0.02, budget=0.02,
                   burn_rate=burn)
    tele.event("admission", reason="queue_full", op="episode.run",
               priority=1, tenant=None, retry_after_s=0.5)
    tele.close()
    client = tmp_path / "client.jsonl"
    tele = telemetry.Telemetry(str(client))
    tele.emit({"kind": "manifest", "run": run})
    _request_line(tele, "t1", "client", run, total_s=0.45)
    tele.close()

    st = stitcher.stitch([str(server), str(client)])
    assert st["unpaired"] == {"alert": 2, "admission": 1}
    by_stream = {s["name"]: s["unpaired"] for s in st["streams"]}
    assert by_stream["server.jsonl"] == {"alert": 2, "admission": 1}
    assert by_stream["client.jsonl"] == {}
    # pairing still exact: the typed noise stole nothing
    (t1,) = st["traces"]
    assert t1["orphan"] is None and st["orphans"] == 0

    assert stitcher.main([str(server), str(client)]) == 0
    out = capsys.readouterr().out
    assert "unpaired typed events: admission=1 alert=2" in out
