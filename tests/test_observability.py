"""Observability tests: DAG exports, causal traces, malformed-DAG dumps,
and the difficulty-adjustment convergence loop.

Reference counterparts: log.ml GraphLogger export, dagtools.ml dot/
GraphML serializers and Exn dump hook, and gym/ocaml/test/test_daa.py.
"""

import collections
from xml.etree import ElementTree as ET

import jax
import numpy as np
import pytest

from cpr_tpu import trace
from cpr_tpu.native import OracleSim
from cpr_tpu.params import make_params


def test_env_state_dag_export():
    from cpr_tpu.envs.bk import BkSSZ

    env = BkSSZ(k=4, max_steps_hint=48)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=32)
    state, obs = jax.jit(env.reset)(jax.random.PRNGKey(0), params)
    step = jax.jit(env.step)
    for _ in range(20):
        state, obs, r, d, i = step(state, env.policies["honest"](obs),
                                   params)
    view = trace.view_of_env_state(state.dag)
    assert len(view.nodes) > 1
    assert all(c > p for c, p in view.edges), "ids are topological"
    dot = trace.to_dot(view)
    assert dot.startswith("digraph") and "->" in dot
    xml = trace.to_graphml(view)
    root = ET.fromstring(xml)  # well-formed
    assert root.tag.endswith("graphml")


def test_oracle_causal_trace_export():
    s = OracleSim("nakamoto", topology="clique", n_nodes=4,
                  activation_delay=10.0, propagation_delay=1.0, seed=1)
    s.run(50)
    view = trace.view_of_oracle(s)
    assert len(view.nodes) == int(s.metric("n_blocks")) + 1
    kinds = collections.Counter(k for _, k, _, _ in view.events)
    assert kinds["appends"] == 50  # one append per activation
    assert kinds["shares"] == 50  # honest nodes share every block
    assert kinds["learns"] >= kinds["appends"]  # deliveries to others
    # events are time-ordered
    times = [t for t, *_ in view.events]
    assert times == sorted(times)
    xml = trace.to_graphml(view)
    root = ET.fromstring(xml)
    ids = {n.get("id") for n in root.iter() if n.tag.endswith("node")}
    assert any(i.startswith("event") for i in ids)
    assert any(i.startswith("vertex") for i in ids)


def test_malformed_dag_dump(tmp_path, monkeypatch):
    target = tmp_path / "malformed.dot"
    monkeypatch.setenv(trace.MALFORMED_ENV_VAR, str(target))
    view = trace.DagView(nodes=[{"id": 0}, {"id": 1}], edges=[(1, 0)])
    with pytest.raises(trace.MalformedDag, match="dumped to"):
        trace.raise_malformed(view, "test failure")
    assert target.exists() and "digraph" in target.read_text()


def test_daa_convergence():
    """The reference DAA feedback test (test_daa.py:7-58): selfish mining
    inflates the block interval; the difficulty-adjustment loop feeding
    observed chain-time/progress back into activation_delay restores the
    target interval."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ

    env = NakamotoSSZ()
    target, eps = 600.0, 25.0
    policy = env.policies["sapirshtein-2016-sm1"]
    # one compile for the whole feedback loop: activation_delay flows in
    # through params
    fn = jax.jit(jax.vmap(
        lambda k, p: env.episode_stats(k, p, policy, 110),
        in_axes=(0, None)))

    def measure(activation_delay, seed):
        params = make_params(alpha=1 / 3, gamma=0.5, max_steps=100,
                             activation_delay=activation_delay)
        keys = jax.random.split(jax.random.PRNGKey(seed), 64)
        stats = jax.block_until_ready(fn(keys, params))
        return (float(np.asarray(stats["episode_chain_time"]).mean()),
                float(np.asarray(stats["episode_progress"]).mean()))

    ct, pr = measure(target, 0)
    assert not (target - eps < ct / pr < target + eps), \
        "selfish mining must push the interval out of tolerance"

    ad = collections.deque([target], maxlen=20)
    cts = collections.deque([ct], maxlen=20)
    prs = collections.deque([pr], maxlen=20)
    for i in range(12):
        next_ad = target * float(np.mean(
            np.array(ad) / np.array(cts) * np.array(prs)))
        ad.append(next_ad)
        ct, pr = measure(next_ad, i + 1)
        cts.append(ct)
        prs.append(pr)
    observed = float(np.sum(cts) / np.sum(prs))
    assert target - eps < observed < target + eps, observed
