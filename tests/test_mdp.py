"""MDP toolbox tests: compiler invariants, value iteration against
literature closed forms, cross-model validation (fc16 vs aft20, mirroring
mdp/lib/models/aft20barzur_test.py), parameter remapping, and the
env <-> MDP equivalence check (the analog of the reference's cross-engine
validation strategy, SURVEY.md §4)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

import jax
from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.models import Aft20BitcoinSM, Fc16BitcoinSM, map_params, mappable_params
from cpr_tpu.mdp.models.bitcoin_sm import ACTIVE, IRRELEVANT, RELEVANT, WAIT


def es2014_revenue(alpha, gamma):
    a, g = alpha, gamma
    return (a * (1 - a) ** 2 * (4 * a + g * (1 - 2 * a)) - a**3) / (
        1 - a * (1 + (2 - a) * a)
    )


def solve(model_cls, alpha, gamma, mfl=40, horizon=100, stop_delta=1e-6):
    c = Compiler(model_cls(alpha=alpha, gamma=gamma, maximum_fork_length=mfl))
    m = ptmdp(c.mdp(), horizon=horizon)
    tm = m.tensor()
    vi = tm.value_iteration(stop_delta=stop_delta)
    rev = tm.start_value(vi["vi_value"]) / tm.start_value(vi["vi_progress"])
    return c, m, tm, vi, rev


def test_compiler_and_check():
    c = Compiler(Fc16BitcoinSM(alpha=0.25, gamma=0.5, maximum_fork_length=10))
    m = c.mdp()
    assert m.check()
    assert m.n_states == len(c.states)
    # truncation: no WAIT available at fork length >= mfl
    for sid, st in enumerate(c.states):
        if st.a >= 10 or st.h >= 10:
            assert WAIT not in c.action_map[sid]


def test_vi_optimal_beats_sm1_and_respects_upper_bound():
    alpha, gamma = 0.35, 0.5
    *_, rev = solve(Fc16BitcoinSM, alpha, gamma)
    lower = es2014_revenue(alpha, gamma)  # optimal >= fixed SM1 strategy
    upper = alpha / (1 - alpha)  # classic selfish-mining upper bound
    assert lower - 0.01 <= rev <= upper + 1e-6, (lower, rev, upper)


def test_vi_honest_region():
    # below the profitability threshold the optimal policy earns ~alpha
    *_, rev = solve(Fc16BitcoinSM, 0.2, 0.0)
    assert abs(rev - 0.2) < 0.01


def test_fc16_vs_aft20_cross_validation():
    # the two literature formulations agree on optimal revenue
    for alpha, gamma in [(0.25, 0.5), (0.4, 0.5)]:
        *_, rev_fc = solve(Fc16BitcoinSM, alpha, gamma, horizon=50)
        *_, rev_bz = solve(Aft20BitcoinSM, alpha, gamma, horizon=50)
        assert abs(rev_fc - rev_bz) < 0.01, (alpha, gamma, rev_fc, rev_bz)


def test_map_params_equals_direct_compilation():
    alpha, gamma = 0.3, 0.6
    c = Compiler(Fc16BitcoinSM(maximum_fork_length=20, **mappable_params))
    base = c.mdp()
    mapped = map_params(base, alpha=alpha, gamma=gamma)
    vi_mapped = ptmdp(mapped, horizon=50).tensor().value_iteration(stop_delta=1e-7)
    c2 = Compiler(Fc16BitcoinSM(alpha=alpha, gamma=gamma, maximum_fork_length=20))
    vi_direct = ptmdp(c2.mdp(), horizon=50).tensor().value_iteration(stop_delta=1e-7)
    np.testing.assert_allclose(
        vi_mapped["vi_value"], vi_direct["vi_value"], rtol=1e-4
    )


def test_policy_evaluation_honest_yields_alpha():
    alpha = 0.3
    c = Compiler(Fc16BitcoinSM(alpha=alpha, gamma=0.5, maximum_fork_length=20))
    m = ptmdp(c.mdp(), horizon=100)
    tm = m.tensor()
    # positional honest policy; the PTO terminal state keeps -1
    policy = np.full(m.n_states, -1, np.int32)
    for sid, st in enumerate(c.states):
        policy[sid] = c.action_map[sid].index(c.model.honest(st))
    pe = tm.policy_evaluation(policy, theta=1e-7)
    rev = tm.start_value(pe["pe_reward"]) / tm.start_value(pe["pe_progress"])
    assert abs(rev - alpha) < 0.005, rev


def test_steady_state_sums_to_one():
    c = Compiler(Fc16BitcoinSM(alpha=0.3, gamma=0.5, maximum_fork_length=10))
    m = ptmdp(c.mdp(), horizon=20)
    tm = m.tensor()
    vi = tm.value_iteration(stop_delta=1e-6)
    start = int(np.argmax(np.asarray(tm.start)))
    ss = tm.steady_state(vi["vi_policy"], start_state=start)
    assert abs(ss["ss"].sum() - 1.0) < 1e-5


def test_sharded_vi_matches_single_device():
    """Transition-sharded VI over the 8-device CPU mesh reproduces the
    single-device solver exactly."""
    from cpr_tpu.parallel import default_mesh, sharded_value_iteration

    c = Compiler(Fc16BitcoinSM(alpha=0.33, gamma=0.7, maximum_fork_length=25))
    tm = ptmdp(c.mdp(), horizon=60).tensor()
    single = tm.value_iteration(stop_delta=1e-6)
    mesh = default_mesh()
    assert mesh.devices.size == 8
    sharded = sharded_value_iteration(tm, mesh, stop_delta=1e-6)
    np.testing.assert_allclose(
        sharded["vi_value"], single["vi_value"], rtol=1e-6, atol=1e-7
    )
    np.testing.assert_array_equal(sharded["vi_policy"], single["vi_policy"])
    # the chunked (device-while-free) sharded impl reaches the same
    # fixpoint — the on-chip capstone path when while_loop faults
    chunked = sharded_value_iteration(tm, mesh, stop_delta=1e-6,
                                      impl="chunked")
    np.testing.assert_allclose(
        chunked["vi_value"], single["vi_value"], rtol=1e-6, atol=1e-7
    )
    np.testing.assert_array_equal(chunked["vi_policy"],
                                  single["vi_policy"])


def test_vi_chunked_impl_matches_while():
    """The device-while-free VI (chunked scan + host convergence, the
    axon-TPU fault workaround) reaches the identical fixpoint, policy
    included; max_iter is honored to within one chunk."""
    c = Compiler(Fc16BitcoinSM(alpha=0.3, gamma=0.5, maximum_fork_length=10))
    tm = ptmdp(c.mdp(), horizon=20).tensor()
    a = tm.value_iteration(stop_delta=1e-9)
    b = tm.value_iteration(stop_delta=1e-9, impl="chunked")
    np.testing.assert_allclose(b["vi_value"], a["vi_value"],
                               rtol=0, atol=1e-12)
    np.testing.assert_array_equal(b["vi_policy"], a["vi_policy"])
    assert b["vi_iter"] >= a["vi_iter"]  # overshoots to a chunk multiple
    fixed = tm.value_iteration(max_iter=7, impl="chunked")
    assert fixed["vi_iter"] == 7
    with pytest.raises(ValueError, match="unknown VI impl"):
        tm.value_iteration(stop_delta=1e-6, impl="nope")


_ANDERSON_SNIPPET = """
import jax, numpy as np, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.models.bitcoin_sm import Fc16BitcoinSM
from cpr_tpu.mdp.explicit import vi_chunked
c = Compiler(Fc16BitcoinSM(alpha=0.35, gamma=0.5, maximum_fork_length=16))
tm = ptmdp(c.mdp(), horizon=100).tensor()
ref = tm.value_iteration(stop_delta=1e-7)
value, prog, pol, delta, it, _ = vi_chunked(
    tm.src, tm.act, tm.dst, tm.prob, tm.reward, tm.progress,
    tm.n_states, tm.n_actions, jnp.float32(1.0), jnp.float32(1e-7),
    1 << 30, accel_m=3)
rev_ref = tm.start_value(ref["vi_value"]) / tm.start_value(ref["vi_progress"])
rev_acc = float(tm.start_value(np.asarray(value))
                / tm.start_value(np.asarray(prog)))
print("RESULT", it, ref["vi_iter"], abs(rev_acc - rev_ref))
"""


def test_vi_anderson_acceleration():
    """Anderson-accelerated chunked VI (the GhostDAG-capstone solver
    path, VERDICT r4 #7) reaches the while-loop fixpoint within the
    stop tolerance in SUBSTANTIALLY fewer sweeps.  Runs in a
    subprocess with PRODUCTION XLA flags: under the suite's
    xla_backend_optimization_level=0 the f32 residuals are noisy
    enough that the safeguard keeps falling back to plain sweeps and
    the speedup shrinks to ~1.2x (measured), which would make the
    assertion meaningless for the real solver config."""
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    out = subprocess.run([sys.executable, "-c", _ANDERSON_SNIPPET],
                         capture_output=True, text=True, check=True,
                         env=env, cwd=os.path.dirname(
                             os.path.dirname(os.path.abspath(__file__))))
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith("RESULT")][0]
    _, it, ref_it, drift = line.split()
    # measured 576 vs 1899 sweeps, drift 1.8e-6; assert conservative
    # bounds so numeric jitter cannot flake the test
    assert float(drift) < 1e-5, line
    assert int(it) < int(ref_it) / 2, line


def test_vi_eps_guard():
    c = Compiler(Fc16BitcoinSM(alpha=0.3, gamma=0.5, maximum_fork_length=8))
    tm = ptmdp(c.mdp(), horizon=20).tensor()
    with pytest.raises(ValueError, match="stop_delta"):
        tm.value_iteration(eps=1e-6)  # discount=1 needs stop_delta
    with pytest.raises(ValueError, match="eps, stop_delta, or max_iter"):
        tm.value_iteration()
    # discounted eps-optimality works
    vi = tm.value_iteration(eps=1e-4, discount=0.9)
    assert vi["vi_iter"] > 1
    # fixed-sweep mode: exactly max_iter Bellman backups
    vi = tm.value_iteration(max_iter=7)
    assert vi["vi_iter"] == 7


def test_env_matches_vi_optimal_policy():
    """Execute the VI-optimal MDP policy inside the JAX environment and
    compare revenues — the cross-engine equivalence test of SURVEY.md §7.2."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ, EV_NETWORK
    from cpr_tpu.params import make_params

    alpha, gamma, mfl = 0.35, 0.9, 50
    c, m, tm, vi, rev_vi = solve(Fc16BitcoinSM, alpha, gamma, mfl=mfl,
                                 horizon=200, stop_delta=1e-7)

    # semantic-action lookup table over (a, h, fork)
    table = np.zeros((mfl + 2, mfl + 2, 3), np.int32)
    for sid, st in enumerate(c.states):
        pos = vi["vi_policy"][sid]
        if pos >= 0:
            table[st.a, st.h, st.fork] = c.action_map[sid][pos]
    jtable = jnp.asarray(table)

    def mdp_policy(state, obs):
        fork = jnp.where(
            state.match_h >= 0, ACTIVE,
            jnp.where(state.event == EV_NETWORK, RELEVANT, IRRELEVANT),
        )
        a = jnp.clip(state.a, 0, mfl + 1)
        h = jnp.clip(state.h, 0, mfl + 1)
        return jtable[a, h, fork]

    mdp_policy.takes_state = True

    env = NakamotoSSZ(strict_match=False)
    params = make_params(alpha=alpha, gamma=gamma, max_steps=1024)
    keys = jax.random.split(jax.random.PRNGKey(11), 512)
    stats = jax.vmap(
        lambda k: env.episode_stats(k, params, mdp_policy, 1200)
    )(keys)
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    rev_env = atk / (atk + dfn)
    assert abs(rev_env - rev_vi) < 0.02, (rev_vi, rev_env)
