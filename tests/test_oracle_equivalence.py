"""Cross-engine equivalence: JAX envs vs the C++ discrete-event oracle.

The reference validates every model against an independent engine
(generic_v1/test/test_network_sim.py, aft20barzur_test.py); here the
collapsed 2-party JAX environments are checked against the multi-node
event-queue simulator (cpr_tpu/native) on reward statistics over an
(alpha, gamma) grid, and both engines are checked against the ES'14
closed form.  Tolerances are statistical (Monte-Carlo on both sides).
"""

import jax
import numpy as np
import pytest

from cpr_tpu.native import OracleSim
from cpr_tpu.params import make_params


def es2014_revenue(a, g):
    return (a * (1 - a) ** 2 * (4 * a + g * (1 - 2 * a)) - a**3) / (
        1 - a * (1 + (2 - a) * a))


def oracle_share(protocol, *, alpha, gamma, policy, activations,
                 seed=0, **kw):
    s = OracleSim(protocol, topology="selfish_mining", alpha=alpha,
                  gamma=gamma, attacker_policy=policy,
                  propagation_delay=1e-9, seed=seed, **kw)
    s.run(activations)
    n = int(1 / (1 - gamma)) + 2 if gamma < 1 else 4
    rw = s.rewards(max(n, 8))
    return rw[0] / sum(rw)


def jax_share(env, *, alpha, gamma, policy, n_envs=1024, max_steps=512):
    params = make_params(alpha=alpha, gamma=gamma, max_steps=max_steps)
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    f = jax.jit(jax.vmap(lambda k: env.episode_stats(
        k, params, env.policies[policy], max_steps + 8)))
    stats = jax.block_until_ready(f(keys))
    a = np.asarray(stats["episode_reward_attacker"]).mean()
    d = np.asarray(stats["episode_reward_defender"]).mean()
    return a / (a + d)


def test_oracle_nakamoto_sm1_matches_closed_form():
    a, g = 1 / 3, 0.5
    share = oracle_share("nakamoto", alpha=a, gamma=g,
                         policy="sapirshtein-2016-sm1", activations=60_000)
    assert abs(share - es2014_revenue(a, g)) < 0.015, share


@pytest.mark.parametrize("alpha,gamma", [(0.25, 0.5), (0.35, 0.5),
                                         (0.4, 0.0)])
def test_nakamoto_sm1_cross_engine(alpha, gamma):
    """SM1 revenue: JAX env vs C++ oracle on the (alpha, gamma) grid."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ

    o = oracle_share("nakamoto", alpha=alpha, gamma=gamma,
                     policy="sapirshtein-2016-sm1", activations=60_000)
    j = jax_share(NakamotoSSZ(), alpha=alpha, gamma=gamma,
                  policy="sapirshtein-2016-sm1")
    assert abs(o - j) < 0.02, (alpha, gamma, o, j)


@pytest.mark.parametrize("alpha", [0.25, 0.4])
def test_nakamoto_honest_cross_engine(alpha):
    from cpr_tpu.envs.nakamoto import NakamotoSSZ

    o = oracle_share("nakamoto", alpha=alpha, gamma=0.5, policy="honest",
                     activations=40_000)
    j = jax_share(NakamotoSSZ(), alpha=alpha, gamma=0.5, policy="honest")
    assert abs(o - alpha) < 0.01, o
    assert abs(o - j) < 0.015, (o, j)


def _two_agents_share(protocol, alpha, activations, seed=0, **kw):
    s = OracleSim(protocol, topology="two_agents", alpha=alpha,
                  activation_delay=1.0, seed=seed, **kw)
    s.run(activations)
    rw = s.rewards(2)
    return rw[0] / sum(rw)


@pytest.mark.slow  # whitepaper-preset anchor; byzantium honest stays
# fast via test_ethereum_attacker_cross_engine[honest]
def test_ethereum_honest_cross_engine():
    """Honest-play reward share: JAX ethereum attack env vs oracle
    two-party network (whitepaper uncles on both sides)."""
    from cpr_tpu.envs.ethereum import EthereumSSZ

    alpha = 0.3
    o = _two_agents_share("ethereum-whitepaper", alpha, 30_000)
    j = jax_share(EthereumSSZ("whitepaper", max_steps_hint=192),
                  alpha=alpha, gamma=0.5, policy="honest",
                  n_envs=256, max_steps=192)
    assert abs(o - alpha) < 0.01, o
    assert abs(j - alpha) < 0.02, j
    assert abs(o - j) < 0.025, (o, j)


def test_bk_honest_cross_engine():
    from cpr_tpu.envs.bk import BkSSZ

    alpha, k = 0.3, 8
    o = _two_agents_share("bk", alpha, 40_000, k=k, scheme="constant")
    j = jax_share(BkSSZ(k=k, incentive_scheme="constant",
                        max_steps_hint=192),
                  alpha=alpha, gamma=0.5, policy="honest",
                  n_envs=256, max_steps=192)
    assert abs(o - alpha) < 0.015, o
    assert abs(j - alpha) < 0.02, j
    assert abs(o - j) < 0.03, (o, j)


@pytest.mark.parametrize("family,oracle_proto,key,okw", [
    ("spar", "spar", "spar-4-constant", dict(k=4, scheme="constant")),
    pytest.param("stree", "stree", "stree-4-discount-heuristic",
                 dict(k=4, scheme="discount"),
                 marks=pytest.mark.slow),  # structure shared with june
    ("sdag", "sdag", "sdag-4-constant-altruistic",
     dict(k=4, scheme="constant")),
    ("tailstorm", "tailstorm", "tailstorm-4-discount-heuristic",
     dict(k=4, scheme="discount")),
    pytest.param("tailstormjune", "tailstormjune",
                 "tailstormjune-4-discount", dict(k=4, scheme="discount"),
                 marks=pytest.mark.slow),  # heaviest compile; tailstorm
    # stays fast as the family's cross-engine representative
    # june's own `block` scheme (the whole k to the summary miner,
    # tailstorm_june.ml:177): an anchor at june's own key that fails if
    # the +block reward scheme drifts on either engine
    pytest.param("tailstormjune", "tailstormjune", "tailstormjune-4-block",
                 dict(k=4, scheme="block"), marks=pytest.mark.slow),
    # selector cross-engine anchors (VERDICT r4 #4): the oracle now
    # implements altruistic/optimal sub-block selection
    # (tailstorm.ml:271-313, :418-506); honest dynamics must agree at
    # each selector's own registry key
    pytest.param("stree", "stree", "stree-4-constant-optimal",
                 dict(k=4, scheme="constant:optimal"),
                 marks=pytest.mark.slow),
    pytest.param("tailstorm", "tailstorm",
                 "tailstorm-4-discount-altruistic",
                 dict(k=4, scheme="discount:altruistic"),
                 marks=pytest.mark.slow),
    pytest.param("tailstorm", "tailstorm",
                 "tailstorm-4-discount-optimal",
                 dict(k=4, scheme="discount:optimal"),
                 marks=pytest.mark.slow),
])
def test_parallel_family_honest_cross_engine(family, oracle_proto, key,
                                             okw):
    """Honest-play reward shares for the parallel-PoW family: JAX attack
    env vs the oracle's multi-node implementation; both must sit at
    alpha and agree (tailstormjune shares stree's protocol structure, so
    the stree oracle is its anchor)."""
    from cpr_tpu.envs import registry

    alpha = 0.3
    o = _two_agents_share(oracle_proto, alpha, 20_000, **okw)
    env = registry.get_sized(key, 96)
    j = jax_share(env, alpha=alpha, gamma=0.5, policy="honest",
                  n_envs=128, max_steps=96)
    assert abs(o - alpha) < 0.02, (family, o)
    assert abs(j - alpha) < 0.03, (family, j)
    assert abs(o - j) < 0.04, (family, o, j)


def test_oracle_orphan_rates_by_difficulty():
    """The reference's stochastic battery shape (cpr_protocols.ml:200-258):
    orphan rate on a 7-node clique must be small at easy difficulty and
    grow as the block interval approaches the propagation delay."""
    rates = {}
    for name, ad in [("easy", 600.0), ("real", 30.0), ("hard", 3.0)]:
        s = OracleSim("nakamoto", topology="clique", n_nodes=7,
                      activation_delay=ad, propagation_delay=1.0, seed=5)
        s.run(3000)
        rates[name] = 1.0 - s.metric("head_height") / s.metric("n_blocks")
    assert rates["easy"] < 0.01, rates
    assert rates["real"] < 0.05, rates
    assert rates["easy"] <= rates["real"] <= rates["hard"], rates
    assert rates["hard"] > 0.1, rates


def test_oracle_clique_fairness():
    """Equal-compute clique: each node's reward share ~ 1/n."""
    s = OracleSim("nakamoto", topology="clique", n_nodes=5,
                  activation_delay=100.0, propagation_delay=1.0, seed=6)
    s.run(20_000)
    rw = np.array(s.rewards(5))
    np.testing.assert_allclose(rw / rw.sum(), 0.2, atol=0.02)


def test_oracle_seeds_are_deterministic():
    a = oracle_share("nakamoto", alpha=0.3, gamma=0.5, policy="honest",
                     activations=5_000, seed=9)
    b = oracle_share("nakamoto", alpha=0.3, gamma=0.5, policy="honest",
                     activations=5_000, seed=9)
    assert a == b


@pytest.mark.parametrize("policy,tol", [
    ("honest", 0.015),
    ("fn19", 0.025),
    pytest.param("fn19pkel", 0.025, marks=pytest.mark.slow),
])
def test_ethereum_attacker_cross_engine(policy, tol):
    """Second attack-space anchor: the oracle's FN'19-style ethereum
    withholding agent vs the JAX env's policies — revenue agreement on
    the byzantium preset (the attack ranking is asserted separately in
    test_ethereum_attack_ranking)."""
    from cpr_tpu.envs.ethereum import EthereumSSZ

    alpha, gamma = 0.35, 0.5
    o = oracle_share("ethereum-byzantium", alpha=alpha, gamma=gamma,
                     policy=policy, activations=60_000)
    # anc_masks=True keeps the masked query backend at full capacity:
    # the walk fallback (the full-mode default) is ~10x slower on CPU
    # for ethereum's visibility-closure releases, and its equivalence
    # to the masked path is already pinned bit-for-bit by
    # test_dag_ring.py::test_ethereum_ring_episode_matches_full.
    env = EthereumSSZ("byzantium", max_steps_hint=192, anc_masks=True)
    j = jax_share(env, alpha=alpha, gamma=gamma, policy=policy,
                  n_envs=256, max_steps=192)
    assert abs(o - j) < tol, (policy, o, j)
    if policy == "honest":
        assert abs(o - alpha) < 0.01, o
    else:
        assert o > alpha + 0.01 and j > alpha + 0.01, (policy, o, j)


@pytest.mark.parametrize("k,policy,alpha,gap,tol", [
    (4, "honest", 0.3, 0.0, 0.015),
    # The get-ahead deviation is STRUCTURAL and STABLE (invariant from
    # 128 to 512 env steps, multi-seed oracle sd ~0.004, two_agents vs
    # selfish_mining topology shift <= 0.007 at gamma <= 0.5), so the
    # anchor pins the characterized gap at +-0.02 instead of allowing
    # 0.06 of slack.  MECHANISM (round-4 decomposition,
    # tools/bk_gap_decompose.py): at k=1 the gap is gym-vs-simulator
    # interaction granularity — the gym engine's `Append` interaction
    # (engine.ml:97-273) lets the attacker re-act immediately after its
    # own proposal lands, the event-driven simulator agent only at the
    # next event; grafting Append granularity onto the oracle
    # ("get-ahead-appendint") closes the k=1 gap 95% (see
    # test_bk_gym_granularity_parity below).  The k=4 residual is
    # DELIVERY-BATCH granularity (round-5 decomposition,
    # test_bk_k4_delivery_batch_parity): the event-loop defender can
    # propose mid-release on a partial vote set, the collapse cannot;
    # the atomic-delivery graft closes it to ~0.002.  These rows keep
    # pinning the UNGRAFTED engines' characterized gap.
    pytest.param(1, "get-ahead", 0.45, +0.0445, 0.02,
                 marks=pytest.mark.slow),
    pytest.param(4, "get-ahead", 0.45, -0.0325, 0.02,
                 marks=pytest.mark.slow),
])
def test_bk_attacker_cross_engine(k, policy, alpha, gap, tol):
    """Third attack-space anchor, vote-based family: the oracle's
    vote-withholding BkAgent vs the JAX env, with the measured
    collapse deviation pinned per k (see parametrize comment)."""
    from cpr_tpu.envs.bk import BkSSZ

    o = oracle_share("bk", alpha=alpha, gamma=0.5, policy=policy,
                     activations=40_000, k=k, scheme="constant")
    env = BkSSZ(k=k, incentive_scheme="constant", max_steps_hint=192)
    j = jax_share(env, alpha=alpha, gamma=0.5, policy=policy,
                  n_envs=256, max_steps=192)
    assert abs((o - j) - gap) < tol, (k, policy, o, j, o - j)
    if policy == "honest":
        assert abs(o - alpha) < 0.012, o
    else:
        assert o > alpha and j > alpha - 0.01, (o, j)


@pytest.mark.slow
def test_bk_k4_delivery_batch_parity():
    """The k=4 get-ahead residual DECOMPOSED (VERDICT r4 #5): it is
    DELIVERY-BATCH granularity, not a multi-defender vote race — the
    single-defender (two_agents) oracle shows the same ~0.037 gap as
    the multi-defender topology (0.4558 vs 0.4603 at the anchor
    settings, round-5 measurement), so defender count is not the
    mechanism.  The event-loop defender runs its handler per delivered
    vertex and can PROPOSE MID-RELEASE on a partial vote set; the env
    collapse applies a release atomically and lets the defender attempt
    one proposal per delivery batch.  Grafting atomic delivery onto the
    oracle ("get-ahead-atomicrel", Sim::atomic_release) closes the gap
    to ~0.002 (0.4924 vs env 0.4944) — pinned here at <= 0.015, the
    same tolerance as the k=1 appendint anchor."""
    from cpr_tpu.envs.bk import BkSSZ

    o = oracle_share("bk", alpha=0.45, gamma=0.5,
                     policy="get-ahead-atomicrel",
                     activations=40_000, k=4, scheme="constant")
    env = BkSSZ(k=4, incentive_scheme="constant", max_steps_hint=192)
    j = jax_share(env, alpha=0.45, gamma=0.5, policy="get-ahead",
                  n_envs=256, max_steps=192)
    assert abs(o - j) < 0.015, (o, j, o - j)


@pytest.mark.slow
def test_bk_gym_granularity_parity():
    """True parity at MATCHED interaction granularity: the oracle's
    get-ahead agent with gym-style Append interactions
    ("get-ahead-appendint": re-act after own proposal at unchanged sim
    time, the engine.ml:97-273 semantics the JAX env implements) agrees
    with the env within 0.015 at k=1/alpha=0.45 — where the plain
    simulator-granularity agent sits +0.044 away (round-4 decomposition,
    tools/bk_gap_decompose.py: 95% of the k=1 gap is granularity)."""
    from cpr_tpu.envs.bk import BkSSZ

    o = oracle_share("bk", alpha=0.45, gamma=0.5,
                     policy="get-ahead-appendint",
                     activations=40_000, k=1, scheme="constant")
    env = BkSSZ(k=1, incentive_scheme="constant", max_steps_hint=192)
    j = jax_share(env, alpha=0.45, gamma=0.5, policy="get-ahead",
                  n_envs=256, max_steps=192)
    assert abs(o - j) < 0.015, (o, j, o - j)


@pytest.mark.parametrize("proto,key,policy,alpha,tol,profitable,okw", [
    # measured cross-engine gaps (20k-act oracle vs 128-env JAX, stable
    # from 128 to 512 steps, so NOT truncation bias): the 2-party
    # collapse treats vote races one interaction at a time, which
    # shifts withholding revenue by 0.01-0.055 depending on family —
    # same class of deviation as the documented bk get-ahead bound.
    ("spar", "spar-4-constant", "selfish", 0.45, 0.035, True, None),
    pytest.param("spar", "spar-4-constant", "selfish", 0.30, 0.03, False,
                 None,
                 marks=pytest.mark.slow),  # unprofitable region agrees too
    ("tailstorm", "tailstorm-4-constant-heuristic", "minor-delay", 0.45,
     0.05, True, None),
    pytest.param("stree", "stree-4-constant-heuristic", "minor-delay",
                 0.45, 0.05, True, None, marks=pytest.mark.slow),
    pytest.param("sdag", "sdag-4-constant-altruistic", "minor-delay",
                 0.45, 0.07, True, None, marks=pytest.mark.slow),
    pytest.param("tailstorm", "tailstorm-4-constant-heuristic",
                 "get-ahead", 0.30, 0.07, False, None,
                 marks=pytest.mark.slow),
    # avoid-loss exercises the Match release path (gamma race arming)
    pytest.param("stree", "stree-4-constant-heuristic", "avoid-loss",
                 0.45, 0.06, True, None, marks=pytest.mark.slow),
    # selector anchors under ATTACK (VERDICT r4 #4): both engines run
    # the same non-default sub-block selection while withholding
    pytest.param("tailstorm", "tailstorm-4-constant-altruistic",
                 "minor-delay", 0.45, 0.05, True,
                 {"scheme": "constant:altruistic"},
                 marks=pytest.mark.slow),
    pytest.param("tailstorm", "tailstorm-4-constant-optimal",
                 "minor-delay", 0.45, 0.05, True,
                 {"scheme": "constant:optimal"},
                 marks=pytest.mark.slow),
    pytest.param("stree", "stree-4-constant-optimal", "minor-delay",
                 0.45, 0.05, True, {"scheme": "constant:optimal"},
                 marks=pytest.mark.slow),
    # june's +block scheme under attack: reward concentration on
    # summary miners changes withholding payoffs; both engines must
    # agree at june's own key.  Measured round 5: oracle ~0.70, env
    # ~0.64 — the whole-k-to-one-miner scheme amplifies the family's
    # collapse/delivery deviation (cf. the 0.05-0.07 sibling rows), so
    # the tolerance pins the characterized ~0.06 gap with MC slack
    pytest.param("tailstormjune", "tailstormjune-4-block", "minor-delay",
                 0.45, 0.09, True, {"scheme": "block"},
                 marks=pytest.mark.slow),
])
def test_parallel_family_attacker_cross_engine(proto, key, policy, alpha,
                                               tol, profitable, okw):
    """Withholding-attack anchors for the parallel-PoW family: the
    oracle's ParAgent (generic SSZ release scan, oracle.cpp) vs the
    JAX attack envs' hard-coded policies — the reference validates
    every attack space with per-protocol policy batteries
    (simulator/protocols/cpr_protocols.ml:478-657)."""
    from cpr_tpu.envs import registry

    o = oracle_share(proto, alpha=alpha, gamma=0.5, policy=policy,
                     activations=30_000, k=4, **(okw or {}))
    env = registry.get_sized(key, 128)
    j = jax_share(env, alpha=alpha, gamma=0.5, policy=policy,
                  n_envs=128, max_steps=128)
    assert abs(o - j) < tol, (proto, policy, o, j)
    if profitable:  # both engines must find the attack profitable
        assert o > alpha and j > alpha, (proto, policy, o, j)
    else:  # ... or agree that withholding loses money here
        assert o < alpha and j < alpha + 0.01, (proto, policy, o, j)


# Characterized cross-engine deviation tables for the (alpha, gamma)
# grids: oracle share minus env share, measured 2026-07 at the exact
# seeds/shapes the grid test uses.  NOTE these pins are PER-SHAPE
# calibrations, not physical constants: the grid runs smaller samples
# (20k activations / 96 reps x 128 steps) than the single-point bk
# anchor (40k / 256 x 192), and the combined MC sem at grid sizes is
# ~0.013 — which is why e.g. bk get-ahead (0.45, 0.5) pins at -0.017
# here but -0.0325 in test_bk_attacker_cross_engine; both centers sit
# within ~1.2 sigma of the same underlying deviation, and each test's
# tolerance covers its own shape's noise.  Honest rows show the multi-node
# concentration drift (selfish_mining splits defenders; vote races
# between them waste defender work, so the single attacker over-earns,
# growing with alpha).  Attacker rows also fold in each env's collapse
# granularity; for tailstorm minor-delay the gap grows with gamma
# because the oracle's delay-based gamma emulation speeds attacker
# deliveries while the env's collapse only expresses gamma in Match
# races (minor-delay never Matches).
_GRID_GAPS = {
    ("bk", "honest"): {
        (0.15, 0.1): +0.003, (0.15, 0.5): +0.002, (0.15, 0.9): -0.009,
        (0.25, 0.1): +0.007, (0.25, 0.5): +0.013, (0.25, 0.9): +0.010,
        (0.33, 0.1): +0.023, (0.33, 0.5): +0.017, (0.33, 0.9): +0.010,
        (0.45, 0.1): +0.031, (0.45, 0.5): +0.032, (0.45, 0.9): +0.036,
    },
    ("bk", "get-ahead"): {
        (0.15, 0.1): -0.055, (0.15, 0.5): -0.053, (0.15, 0.9): -0.049,
        (0.25, 0.1): -0.085, (0.25, 0.5): -0.082, (0.25, 0.9): -0.072,
        (0.33, 0.1): -0.077, (0.33, 0.5): -0.066, (0.33, 0.9): -0.064,
        (0.45, 0.1): -0.019, (0.45, 0.5): -0.017, (0.45, 0.9): -0.002,
    },
    # spar and sdag honest dynamics coincide exactly under shared
    # seeds in both engines (PoW-proportional rewards, no withholding),
    # so one table serves both families
    ("spar", "honest"): {
        (0.15, 0.1): -0.005, (0.15, 0.5): -0.006, (0.15, 0.9): -0.004,
        (0.25, 0.1): -0.002, (0.25, 0.5): +0.001, (0.25, 0.9): +0.003,
        (0.33, 0.1): +0.013, (0.33, 0.5): +0.008, (0.33, 0.9): +0.005,
        (0.45, 0.1): +0.011, (0.45, 0.5): +0.011, (0.45, 0.9): +0.007,
    },
    ("tailstorm", "honest"): {
        (0.15, 0.1): -0.004, (0.15, 0.5): -0.005, (0.15, 0.9): -0.004,
        (0.25, 0.1): -0.002, (0.25, 0.5): +0.003, (0.25, 0.9): +0.002,
        (0.33, 0.1): +0.013, (0.33, 0.5): +0.005, (0.33, 0.9): +0.005,
        (0.45, 0.1): +0.012, (0.45, 0.5): +0.013, (0.45, 0.9): +0.008,
    },
    ("tailstorm", "minor-delay"): {
        (0.15, 0.1): +0.007, (0.15, 0.5): +0.035, (0.15, 0.9): +0.064,
        (0.25, 0.1): +0.013, (0.25, 0.5): +0.051, (0.25, 0.9): +0.074,
        (0.33, 0.1): +0.030, (0.33, 0.5): +0.033, (0.33, 0.9): +0.071,
        (0.45, 0.1): +0.046, (0.45, 0.5): +0.057, (0.45, 0.9): +0.073,
    },
}
# measured identical under shared seeds (see the spar table's comment)
_GRID_GAPS[("sdag", "honest")] = _GRID_GAPS[("spar", "honest")]


@pytest.mark.slow
@pytest.mark.parametrize("oproto,key,policy,okw", [
    ("bk", "bk-4-constant", "honest", dict(scheme="constant")),
    ("bk", "bk-4-constant", "get-ahead", dict(scheme="constant")),
    ("tailstorm", "tailstorm-4-constant-heuristic", "honest",
     dict(scheme="constant")),
    ("tailstorm", "tailstorm-4-constant-heuristic", "minor-delay",
     dict(scheme="constant")),
    ("spar", "spar-4-constant", "honest", dict(scheme="constant")),
    ("sdag", "sdag-4-constant-altruistic", "honest",
     dict(scheme="constant")),
])
def test_cross_engine_alpha_gamma_grid(oproto, key, policy, okw):
    """(alpha x gamma) grid anchors (VERDICT r2 #7): single-point checks
    can miss semantic bugs smaller than their tolerance; the grid pins
    the characterized deviation at EVERY point to +-0.03 (honest
    +-0.02), so a regression in either engine of ~2 binomial sigmas
    fails.  The env side runs the whole grid as one batched kernel
    (withholding_rows); the oracle side is one short event-sim per
    point.  Reference battery shape: cpr_protocols.ml:200-477."""
    from cpr_tpu.experiments import withholding_rows

    gaps = _GRID_GAPS[(oproto, policy)]
    alphas = sorted({a for a, _ in gaps})
    gammas = sorted({g for _, g in gaps})
    rows = withholding_rows(key, policies=[policy], alphas=alphas,
                            gammas=gammas, episode_len=128, reps=96)
    assert not any(r.get("error") for r in rows), rows
    tol = 0.02 if policy == "honest" else 0.03
    for r in rows:
        o = oracle_share(oproto, alpha=r["alpha"], gamma=r["gamma"],
                         policy=policy, activations=20_000, k=4, **okw)
        gap = gaps[(r["alpha"], r["gamma"])]
        j = r["relative_reward"]
        assert abs((o - j) - gap) < tol, \
            (oproto, policy, r["alpha"], r["gamma"], o, j, o - j, gap)
        if policy == "honest":
            assert abs(j - r["alpha"]) < 0.02, (key, r)


def test_parallel_family_attack_ranking():
    """Oracle-only sanity (cheap, no JAX compiles): at alpha=0.45 the
    withholding policies must beat honest play within each family."""
    shares = {}
    for proto, pol in [("stree", "honest"), ("stree", "minor-delay"),
                       ("tailstorm", "honest"),
                       ("tailstorm", "minor-delay")]:
        shares[(proto, pol)] = oracle_share(
            proto, alpha=0.45, gamma=0.5, policy=pol,
            activations=20_000, k=4)
    assert shares[("stree", "minor-delay")] > \
        shares[("stree", "honest")] + 0.05
    assert shares[("tailstorm", "minor-delay")] > \
        shares[("tailstorm", "honest")] + 0.05


def test_ethereum_attack_ranking():
    """The oracle must rank the ethereum attacks fn19pkel > fn19 >
    honest at alpha=0.35 (oracle-only: cheap, no JAX compiles)."""
    shares = {p: oracle_share("ethereum-byzantium", alpha=0.35, gamma=0.5,
                              policy=p, activations=60_000)
              for p in ("honest", "fn19", "fn19pkel")}
    assert shares["fn19pkel"] > shares["fn19"] > shares["honest"], shares
