"""PPO trainer tests: learning signal on the selfish-mining env and the
multi-chip dry run on the virtual CPU mesh."""

import pytest

import numpy as np

import jax

from cpr_tpu.envs.nakamoto import NakamotoSSZ
from cpr_tpu.params import make_params
from cpr_tpu.train.ppo import PPOConfig, train

# deep stochastic battery: opt-in (fast coverage lives in
# test_protocol_smoke.py)
pytestmark = pytest.mark.slow


def rel(h):
    a, d = h["episode_reward_attacker"], h["episode_reward_defender"]
    return a / (a + d + 1e-9)


def test_ppo_improves_attacker_revenue():
    # at (alpha=0.45, gamma=0.9) selfish mining is very profitable
    # (ES'14 closed form ~0.74); PPO must climb away from the random init
    env = NakamotoSSZ()
    params = make_params(alpha=0.45, gamma=0.9, max_steps=128)
    cfg = PPOConfig(n_envs=64, n_steps=128, lr=1e-3, entropy_coef=0.02)
    _, hist = train(env, params, cfg, n_updates=40, seed=0)
    early = np.mean([rel(h) for h in hist[:5]])
    late = np.mean([rel(h) for h in hist[-5:]])
    assert late > early + 0.05, (early, late)
    assert np.isfinite([h["pg_loss"] for h in hist]).all()


def test_graft_entry_single_chip():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    logits, value = jax.jit(fn)(*args)
    assert logits.shape == (256, 4) and value.shape == (256,)


def test_graft_entry_multichip():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
