"""netsim engine tests: compile-time topology planes, engine
validation, scan/event cross-checks, telemetry wiring, and the
statistical parity battery against the unmodified C++ oracle
(slow tier; PARITY.md records the measured bands).

Fast tier keeps to tiny shapes — the compile budget, not the step
count, dominates here.
"""

import io
import json

import numpy as np
import pytest

from cpr_tpu import distributions as dist
from cpr_tpu import netsim
from cpr_tpu import network as netlib
from cpr_tpu import telemetry


def _clique(n=5, ad=50.0, pd=1.0):
    return netlib.symmetric_clique(n, activation_delay=ad,
                                   propagation_delay=pd)


def _orphan(out, activations):
    return 1.0 - np.asarray(out["progress"]) / float(activations)


def _assert_clean(out, activations):
    """Invariants every healthy run satisfies: zero overflow, rewards
    sum to the head chain, node activations sum to the lane total."""
    for key in ("drop_q", "drop_p", "drop_b", "win_miss"):
        assert not np.any(out[key]), (key, out[key])
    assert not np.any(out["exhausted"])
    assert np.all(out["node_act"].sum(axis=1) == activations)
    # constant scheme: one unit per confirmed PoW item == progress
    # (nakamoto: chain height; bk: k quorum votes per proposal)
    np.testing.assert_allclose(out["reward"].sum(axis=1),
                               out["progress"], rtol=1e-6)


def test_compile_network_planes():
    net = _clique(4, ad=30.0, pd=2.0)
    cn = netsim.compile_network(net)
    assert cn.n == 4 and not cn.flooding
    assert cn.compute.shape == (4,)
    np.testing.assert_allclose(cn.compute.sum(), 1.0, rtol=1e-6)
    off = ~np.eye(4, dtype=bool)
    assert np.all(cn.kind[off] == netsim.NETSIM_KINDS["constant"])
    assert np.all(cn.kind[~off] == -1)
    assert np.all(cn.p0[off] == 2.0)


def test_compile_network_rejections():
    with pytest.raises(ValueError, match="at least 2 nodes"):
        netsim.compile_network(netlib.Network(
            nodes=[netlib.NetNode(1.0)], activation_delay=1.0))
    bad = netlib.Network(
        nodes=[netlib.NetNode(0.5, [netlib.Link(1, dist.discrete([1, 2]))]),
               netlib.NetNode(0.5, [netlib.Link(0, dist.constant(1.0))])],
        activation_delay=1.0)
    with pytest.raises(ValueError, match="not 'discrete'"):
        netsim.compile_network(bad)
    with pytest.raises(ValueError, match="unknown dissemination"):
        netsim.compile_network(netlib.Network(
            nodes=_clique().nodes, activation_delay=1.0,
            dissemination="telepathy"))
    # geometric is netsim-only (the oracle rejects it): compiles fine
    geo = netlib.Network(
        nodes=[netlib.NetNode(0.5, [netlib.Link(1, dist.geometric(0.5))]),
               netlib.NetNode(0.5, [netlib.Link(0, dist.geometric(0.5))])],
        activation_delay=1.0)
    assert netsim.compile_network(geo).kind[0, 1] == \
        netsim.NETSIM_KINDS["geometric"]


def test_engine_validation():
    net = _clique()
    with pytest.raises(ValueError, match="supports protocols"):
        netsim.Engine(net, protocol="tailstorm", activations=100)
    with pytest.raises(ValueError, match="k >= 1"):
        netsim.Engine(net, protocol="bk", k=0, activations=100)
    with pytest.raises(ValueError, match="mode must be"):
        netsim.Engine(net, activations=100, mode="warp")
    with pytest.raises(ValueError, match="scan mode needs nakamoto"):
        netsim.Engine(net, protocol="bk", k=2, activations=100,
                      mode="scan")
    eng = netsim.Engine(net, activations=100)
    assert eng.mode == "scan"  # auto picks the fast path
    assert netsim.Engine(net, activations=100, mode="event").mode \
        == "event"
    assert netsim.Engine(net, protocol="bk", k=2,
                         activations=100).mode == "event"
    with pytest.raises(ValueError, match="pair up"):
        eng.run([0, 1], [50.0])
    assert netsim.supports("nakamoto", 1, "constant")
    assert netsim.supports("bk", 8, "block")
    assert not netsim.supports("tailstorm", 8, "constant")
    assert not netsim.supports("bk", 8, "discount")


def test_grid_helper():
    ss, dd = netsim.grid([0, 1], [30.0, 60.0])
    assert ss == [0, 1, 0, 1]
    assert dd == [30.0, 30.0, 60.0, 60.0]


def test_scan_lane_matches_single_lane():
    """vmap determinism: lane i of a batched run reproduces the same
    (seed, delay) run bit-for-bit in a 1-lane batch."""
    eng = netsim.Engine(_clique(), activations=300)
    batch = eng.run([0, 1, 2, 3], [40.0, 40.0, 160.0, 160.0])
    solo = eng.run([2], [160.0])
    for key in ("head_height", "progress", "sim_time"):
        assert np.asarray(batch[key])[2] == np.asarray(solo[key])[0], key
    np.testing.assert_array_equal(batch["reward"][2], solo["reward"][0])
    _assert_clean(batch, 300)


def test_scan_matches_event_engine_stats():
    """Both execution modes describe the same process: orphan rates on
    a constant-delay clique agree within sampling noise (the RNG draw
    order differs, so runs are statistically — not bitwise — equal)."""
    net = _clique(5, pd=1.0)
    seeds, delays = netsim.grid([0, 1, 2, 3], [25.0])
    a = 800
    scan = netsim.Engine(net, activations=a, mode="scan").run(
        seeds, delays)
    event = netsim.Engine(net, activations=a, mode="event").run(
        seeds, delays)
    _assert_clean(scan, a)
    _assert_clean(event, a)
    gap = abs(float(_orphan(scan, a).mean())
              - float(_orphan(event, a).mean()))
    assert gap < 0.02, (gap, _orphan(scan, a), _orphan(event, a))


def test_bk_event_engine_invariants():
    out = netsim.Engine(_clique(), protocol="bk", k=2,
                        activations=400).run([0, 1], [50.0, 200.0])
    _assert_clean(out, 400)
    hh = np.asarray(out["head_height"])
    assert np.all(hh > 0)
    # k=2: roughly one proposal per 2 activations reaches the chain
    assert np.all(hh < 400)


def test_ethereum_event_engine_invariants():
    """Both ethereum variants run on the event engine: zero overflow,
    rewards bounded by [height, activations] (miner 1/block + bounded
    uncle terms), byzantium progress counts uncle work."""
    for proto in ("ethereum-whitepaper", "ethereum-byzantium"):
        out = netsim.Engine(_clique(), protocol=proto,
                            activations=400).run([0, 1], [50.0, 200.0])
        for key in ("drop_q", "drop_p", "drop_b", "win_miss"):
            assert not np.any(out[key]), (proto, key, out[key])
        assert not np.any(out["exhausted"])
        assert np.all(out["node_act"].sum(axis=1) == 400)
        hh = np.asarray(out["head_height"])
        prog = np.asarray(out["progress"])
        rew = np.asarray(out["reward"]).sum(axis=1)
        onc = np.asarray(out["on_chain"])
        if proto == "ethereum-byzantium":
            # progress = work on the preferred tip >= chain height
            assert np.all(prog >= hh), (prog, hh)
        else:
            np.testing.assert_allclose(prog, hh)
        assert np.all(rew >= hh - 1e-6), (proto, rew, hh)
        assert np.all(rew <= 400.0 + 1e-6), (proto, rew)
        # uncles land on chain alongside the linear ancestry
        assert np.all(onc >= hh), (proto, onc, hh)


def test_spar_event_engine_invariants():
    """Spar on the event engine: every k activations close one height
    (one block + k-1 votes), rewards sum to k per height for both
    reward schemes, progress = height * k."""
    for scheme in ("constant", "block"):
        out = netsim.Engine(_clique(), protocol="spar", k=4,
                            scheme=scheme,
                            activations=400).run([0, 1], [50.0, 200.0])
        _assert_clean(out, 400)
        hh = np.asarray(out["head_height"])
        np.testing.assert_allclose(np.asarray(out["progress"]), hh * 4)
        # 400 activations / k=4 => ~100 heights, minus orphaned votes
        assert np.all(hh > 80) and np.all(hh <= 100), hh
    # k=1 degenerates to a nakamoto-like chain
    out = netsim.Engine(_clique(), protocol="spar", k=1,
                        activations=300).run([0], [60.0])
    _assert_clean(out, 300)


def test_netsim_emits_typed_event_and_spans(tmp_path):
    """The engine's telemetry lands as schema-valid artifacts: fenced
    netsim:run spans plus the typed `netsim` point event."""
    buf = io.StringIO()
    telemetry.configure(stream=buf)
    try:
        netsim.Engine(_clique(), activations=200).run([0], [60.0])
    finally:
        telemetry.configure(None)  # don't leak a sink into other tests
    events = [json.loads(line) for line in
              buf.getvalue().strip().split("\n")]
    spans = {e["name"] for e in events if e["kind"] == "span"}
    assert {"netsim:compile", "netsim:run"} <= spans
    ev = [e for e in events
          if e["kind"] == "event" and e["name"] == "netsim"]
    assert len(ev) == 1
    for field in telemetry.EVENT_FIELDS["netsim"]:
        assert field in ev[0], field
    assert ev[0]["drops"] == 0 and ev[0]["lanes"] == 1


def test_honest_net_rows_jax_schema():
    """engine="jax" fills the exact oracle row schema; protocols netsim
    lacks degrade to error rows like unknown protocols do."""
    from cpr_tpu.experiments import honest_net_rows

    kw = dict(activation_delays=(60.0, 600.0), n_nodes=5,
              n_activations=500)
    oracle = honest_net_rows(protocols=(("nakamoto", {}),), **kw)
    jaxr = honest_net_rows(
        protocols=(("nakamoto", {}),
                   ("tailstorm", dict(k=8, scheme="constant"))),
        engine="jax", **kw)
    ok = [r for r in jaxr if "error" not in r]
    bad = [r for r in jaxr if "error" in r]
    assert len(ok) == 2 and len(bad) == 1
    assert bad[0]["protocol"] == "tailstorm"
    assert "netsim supports protocols" in bad[0]["error"]
    # machine-readable error class: tools filter on `reason` instead
    # of parsing the message (the column shrinks as ports land)
    assert bad[0]["reason"] == "unsupported-protocol"
    assert set(oracle[0]) == set(ok[0])
    for r in ok:
        assert r["engine"] == "jax"
        assert 0.0 <= r["orphan_rate"] < 0.2
        assert r["machine_duration_s"] > 0
        acts = [int(x) for x in r["node_activations"].split("|")]
        assert sum(acts) == r["activations"]


# -- slow tier: statistical parity + wall-clock vs the oracle ---------------


def _timed(fn, *args, now):
    t0 = now()
    fn(*args)
    return now() - t0


def _oracle_orphan(proto, kw, n_nodes, ad, a, seed):
    from cpr_tpu.native import OracleSim

    s = OracleSim(proto, topology="clique", n_nodes=n_nodes,
                  activation_delay=ad, propagation_delay=1.0,
                  seed=seed, **kw)
    try:
        s.run(a)
        return max(0.0, 1.0 - s.metric("progress") / a)
    finally:
        s.close()


@pytest.mark.slow
def test_parity_nakamoto_grid_vs_oracle():
    """Acceptance battery: 10-node clique, 3 activation delays x 8
    seeds x 10k activations.  Per-delay mean orphan rates match the
    unmodified oracle within the PARITY.md band."""
    n, a = 10, 10_000
    delays = (30.0, 60.0, 120.0)
    seeds = tuple(range(8))
    oracle = {ad: [_oracle_orphan("nakamoto", {}, n, ad, a, s)
                   for s in seeds] for ad in delays}

    ss, dd = netsim.grid(seeds, delays)
    out = netsim.Engine(_clique(n), activations=a).run(ss, dd)
    _assert_clean(out, a)

    orphan = _orphan(out, a).reshape(len(delays), len(seeds))
    for i, ad in enumerate(delays):
        gap = abs(float(orphan[i].mean()) - float(np.mean(oracle[ad])))
        # band: 8-seed means of a ~binomial(10k, p) rate; see PARITY.md
        assert gap < 0.006, (ad, orphan[i], oracle[ad])
    # delay monotonicity survives the engine swap
    assert orphan[0].mean() > orphan[2].mean()


_WALLCLOCK_CHILD = """
import json
from cpr_tpu import netsim, network
from cpr_tpu.native import OracleSim
from cpr_tpu.telemetry import now

n, a = 10, 10_000
delays, seeds = (30.0, 60.0, 120.0), tuple(range(8))
t0 = now()
for ad in delays:
    for s in seeds:
        sim = OracleSim("nakamoto", topology="clique", n_nodes=n,
                        activation_delay=ad, propagation_delay=1.0,
                        seed=s)
        sim.run(a)
        sim.close()
oracle_s = now() - t0
net = network.symmetric_clique(n, activation_delay=30.0,
                               propagation_delay=1.0)
ss, dd = netsim.grid(seeds, delays)
eng = netsim.Engine(net, activations=a)
t0 = now()
out = eng.run(ss, dd)
first_s = now() - t0
netsim_s = first_s
for _ in range(3):
    t0 = now()
    out = eng.run(ss, dd)
    netsim_s = min(netsim_s, now() - t0)
drops = int(out["drop_q"].sum() + out["drop_p"].sum()
            + out["drop_b"].sum() + out["win_miss"].sum())
print(json.dumps(dict(oracle_s=oracle_s, netsim_first_s=first_s,
                      netsim_s=netsim_s, drops=drops)))
"""


@pytest.mark.slow
def test_netsim_beats_serial_oracle_wallclock():
    """The 24-lane batched netsim run (one device program, cached
    executable, best-of-3) beats the serial oracle loop on the same
    grid.  Measured in a child process with default XLA_FLAGS: the
    conftest mesh sets --xla_backend_optimization_level=0 (a compile-
    time/runtime trade that's right for the suite), which deoptimizes
    exactly the codegen this comparison is about, while leaving the
    C++ oracle untouched."""
    import json
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, "-c", _WALLCLOCK_CHILD], env=env,
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert res.returncode == 0, res.stderr[-2000:]
    stats = json.loads(res.stdout.strip().splitlines()[-1])
    print(f"\nnetsim 24-lane cached: {stats['netsim_s']:.3f}s "
          f"(compile+first {stats['netsim_first_s']:.2f}s); "
          f"oracle serial 24 runs: {stats['oracle_s']:.3f}s")
    assert stats["drops"] == 0
    assert stats["netsim_s"] < stats["oracle_s"], stats


@pytest.mark.slow
def test_parity_bk_event_engine():
    """The general event engine (bk k=8: non-PoW proposals, votes,
    quorums) tracks the oracle's orphan rates on the same grid.  Kept
    to 4 lanes x 4k activations: the event engine's per-step cost
    scales with the ledger capacity under vmap (batched scatters copy
    the (B,) planes per lane), so the full 10k grid runs ~20 min."""
    n, a = 10, 4_000
    kw = dict(k=8, scheme="constant")
    delays = (30.0, 120.0)
    seeds = (0, 1)
    oracle = {ad: np.mean([_oracle_orphan("bk", kw, n, ad, a, s)
                           for s in seeds]) for ad in delays}
    ss, dd = netsim.grid(seeds, delays)
    out = netsim.Engine(_clique(n), protocol="bk", k=8,
                        activations=a).run(ss, dd)
    _assert_clean(out, a)
    orphan = _orphan(out, a).reshape(len(delays), len(seeds))
    for i, ad in enumerate(delays):
        gap = abs(float(orphan[i].mean()) - float(oracle[ad]))
        assert gap < 0.006, (ad, orphan[i], oracle[ad])


def _parity_reduced(proto, kw, band=0.006):
    """Reduced event-engine parity grid (see test_parity_bk_event_engine
    for why it stays at 4 lanes x 4k activations)."""
    n, a = 10, 4_000
    delays = (30.0, 120.0)
    seeds = (0, 1)
    oracle = {ad: np.mean([_oracle_orphan(proto, kw, n, ad, a, s)
                           for s in seeds]) for ad in delays}
    ss, dd = netsim.grid(seeds, delays)
    out = netsim.Engine(_clique(n), protocol=proto, activations=a,
                        **kw).run(ss, dd)
    for key in ("drop_q", "drop_p", "drop_b", "win_miss"):
        assert not np.any(out[key]), (proto, key, out[key])
    assert not np.any(out["exhausted"])
    orphan = _orphan(out, a).reshape(len(delays), len(seeds))
    for i, ad in enumerate(delays):
        gap = abs(float(orphan[i].mean()) - float(oracle[ad]))
        assert gap < band, (proto, ad, orphan[i], oracle[ad])


@pytest.mark.slow
def test_parity_ethereum_whitepaper_event_engine():
    """Ethereum (whitepaper uncle accounting) vs the unmodified oracle:
    progress = chain height, so orphan rate exercises the work-based
    preference + uncle window jointly."""
    _parity_reduced("ethereum-whitepaper", {})


@pytest.mark.slow
def test_parity_ethereum_byzantium_event_engine():
    """Byzantium variant: height-based preference, uncle cap 2,
    progress = tip work (uncles count), so the measured 'orphan rate'
    is the work the network failed to absorb."""
    _parity_reduced("ethereum-byzantium", {})


@pytest.mark.slow
def test_parity_spar_event_engine():
    """Spar k=4 vs the oracle: vote-confirmation gating means orphans
    are votes on the losing branch; progress = height * k on both
    sides."""
    _parity_reduced("spar", dict(k=4, scheme="constant"))
