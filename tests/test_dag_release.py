"""Release-primitive semantics on hand-built DAGs.

The reference's share is fully recursive (simulator.ml:401-419): making a
block visible shares every withheld ancestor.  `release_chain` covers the
chain+row-rider case in O(newly released); `release_closure` adds the
visibility fixpoint needed when a released row-rider carries its OWN
withheld parents (ethereum uncles-of-uncles)."""

import jax.numpy as jnp

from cpr_tpu.core import dag as D


def _nested_uncle_dag():
    """root <- W (withheld);  U parents [root, W] (withheld);
    X parents [root, U] (withheld).  Releasing X must transitively
    reveal U (row rider of X) AND W (row rider of U): the reference's
    recursive share would."""
    dag = D.empty(8, 2)
    dag, root = D.append(dag, jnp.array([D.NONE, D.NONE], jnp.int32),
                         kind=0, height=0, vis_a=True, vis_d=True,
                         time=0.0)
    dag, w = D.append(dag, jnp.array([0, D.NONE], jnp.int32),
                      kind=0, height=1, vis_a=True, vis_d=False, time=1.0)
    dag, u = D.append(dag, jnp.array([0, 1], jnp.int32),
                      kind=0, height=1, vis_a=True, vis_d=False, time=2.0)
    dag, x = D.append(dag, jnp.array([0, 2], jnp.int32),
                      kind=0, height=1, vis_a=True, vis_d=False, time=3.0)
    return dag, root, w, u, x


def test_release_closure_reveals_nested_row_riders():
    dag, root, w, u, x = _nested_uncle_dag()
    out = D.release_closure(dag, jnp.int32(int(x)), 9.0)
    assert bool(out.vis_d[x]) and bool(out.vis_d[u]) and bool(out.vis_d[w])
    # matches the full recursive share
    ref = D.release_with_ancestors(dag, jnp.int32(int(x)), 9.0)
    assert (out.vis_d == ref.vis_d).all()


def test_release_chain_alone_misses_nested_rider():
    """Documents WHY release_closure exists: the chain walk releases X's
    row (revealing U) but never walks U, so W stays withheld."""
    dag, root, w, u, x = _nested_uncle_dag()
    out = D.release_chain(dag, jnp.int32(int(x)), 9.0)
    assert bool(out.vis_d[u]) and not bool(out.vis_d[w])


def test_release_closure_noop_on_negative_tip():
    dag, *_ = _nested_uncle_dag()
    out = D.release_closure(dag, jnp.int32(-1), 9.0)
    assert (out.vis_d == dag.vis_d).all()


def test_lifted_walks_match_linear():
    """Property test: lifted (binary-jump) walk_back and LCA equal the
    linear implementations on random unit-height-increment chain forests
    — the fast-tier guard for the jump logic (the lifted user, ethereum,
    is otherwise only covered by the slow tier)."""
    import numpy as np

    rng = np.random.default_rng(7)
    for trial in range(5):
        B, P = 96, 3
        dU = D.empty(B, P)
        dL = D.empty(B, P, lift=True)
        row0 = jnp.full((P,), D.NONE, jnp.int32)
        tips = []  # (slot, height)

        def app(d, parent, h):
            row = row0 if parent < 0 else row0.at[0].set(parent)
            d, i = D.append(d, row, height=h)
            return d, int(i)

        dU, r = app(dU, -1, 0)
        dL, _ = app(dL, -1, 0)
        tips.append((r, 0))
        for _ in range(70):
            p, h = tips[rng.integers(len(tips))]
            dU, i = app(dU, p, h + 1)
            dL, _ = app(dL, p, h + 1)
            tips.append((i, h + 1))
        slots = [s for s, _ in tips]
        for _ in range(12):
            a, b = rng.choice(slots, 2)
            caU = int(D.common_ancestor_by_height(dU, jnp.int32(a),
                                                  jnp.int32(b)))
            caL = int(D.common_ancestor_by_height(dL, jnp.int32(a),
                                                  jnp.int32(b)))
            assert caU == caL, (trial, a, b, caU, caL)
            tgt = int(rng.integers(0, 40))
            wU = int(D.block_at_height(dU, jnp.int32(a), tgt))
            wL = int(D.block_at_height(dL, jnp.int32(a), tgt))
            assert wU == wL, (trial, a, tgt, wU, wL)
