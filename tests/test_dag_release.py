"""Release-primitive semantics on hand-built DAGs.

The reference's share is fully recursive (simulator.ml:401-419): making a
block visible shares every withheld ancestor.  `release_chain` covers the
chain+row-rider case in O(newly released); `release_closure` adds the
visibility fixpoint needed when a released row-rider carries its OWN
withheld parents (ethereum uncles-of-uncles)."""

import jax.numpy as jnp

from cpr_tpu.core import dag as D


def _nested_uncle_dag():
    """root <- W (withheld);  U parents [root, W] (withheld);
    X parents [root, U] (withheld).  Releasing X must transitively
    reveal U (row rider of X) AND W (row rider of U): the reference's
    recursive share would."""
    dag = D.empty(8, 2)
    dag, root = D.append(dag, jnp.array([D.NONE, D.NONE], jnp.int32),
                         kind=0, height=0, vis_a=True, vis_d=True,
                         time=0.0)
    dag, w = D.append(dag, jnp.array([0, D.NONE], jnp.int32),
                      kind=0, height=1, vis_a=True, vis_d=False, time=1.0)
    dag, u = D.append(dag, jnp.array([0, 1], jnp.int32),
                      kind=0, height=1, vis_a=True, vis_d=False, time=2.0)
    dag, x = D.append(dag, jnp.array([0, 2], jnp.int32),
                      kind=0, height=1, vis_a=True, vis_d=False, time=3.0)
    return dag, root, w, u, x


def test_release_closure_reveals_nested_row_riders():
    dag, root, w, u, x = _nested_uncle_dag()
    out = D.release_closure(dag, jnp.int32(int(x)), 9.0)
    assert bool(out.vis_d[x]) and bool(out.vis_d[u]) and bool(out.vis_d[w])
    # matches the full recursive share
    ref = D.release_with_ancestors(dag, jnp.int32(int(x)), 9.0)
    assert (out.vis_d == ref.vis_d).all()


def test_release_chain_alone_misses_nested_rider():
    """Documents WHY release_closure exists: the chain walk releases X's
    row (revealing U) but never walks U, so W stays withheld."""
    dag, root, w, u, x = _nested_uncle_dag()
    out = D.release_chain(dag, jnp.int32(int(x)), 9.0)
    assert bool(out.vis_d[u]) and not bool(out.vis_d[w])


def test_release_closure_noop_on_negative_tip():
    dag, *_ = _nested_uncle_dag()
    out = D.release_closure(dag, jnp.int32(-1), 9.0)
    assert (out.vis_d == dag.vis_d).all()
