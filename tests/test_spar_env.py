"""Spar env tests, mirroring the reference's stochastic batteries
(cpr_protocols.ml:200-657) and spar.ml:100-117 validity."""

import jax
import numpy as np
import pytest

from cpr_tpu.envs.spar import BLOCK, VOTE, SparSSZ
from cpr_tpu.params import make_params

# deep stochastic battery: opt-in (fast coverage lives in
# test_protocol_smoke.py)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def env():
    return SparSSZ(k=4, incentive_scheme="constant", max_steps_hint=192)


def run_policy(env, name, alpha, n_envs=128, episode_steps=128, seed=0):
    params = make_params(alpha=alpha, gamma=0.5, max_steps=episode_steps)
    policy = env.policies[name]
    keys = jax.random.split(jax.random.PRNGKey(seed), n_envs)
    stats = jax.vmap(
        lambda k: env.episode_stats(k, params, policy, episode_steps + 32)
    )(keys)
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    return atk / (atk + dfn)


def test_honest_policy_yields_alpha(env):
    for alpha in [0.25, 0.4]:
        rel = run_policy(env, "honest", alpha)
        assert abs(rel - alpha) < 0.05, (alpha, rel)


def test_dag_structure_invariants(env):
    """spar.ml:100-117: votes have one block parent at the same height;
    blocks have a block parent at height-1 plus exactly k-1 votes on it."""
    params = make_params(alpha=0.35, gamma=0.5, max_steps=160)
    state, obs = env.reset(jax.random.PRNGKey(3), params)
    step = jax.jit(env.step)
    policy = env.policies["selfish"]
    for _ in range(160):
        state, obs, r, done, info = step(state, policy(obs), params)
    dag = state.dag
    n = int(dag.n)
    assert not bool(dag.overflow)
    parents = np.stack([np.asarray(q) for q in dag.parents], axis=1)[:n]
    kind = np.asarray(dag.kind)[:n]
    height = np.asarray(dag.height)[:n]
    signer = np.asarray(dag.signer)[:n]
    powh = np.asarray(dag.pow_hash)[:n]
    saw_block = False
    for i in range(1, n):
        ps = parents[i][parents[i] >= 0]
        assert np.isfinite(powh[i])
        if kind[i] == VOTE:
            assert len(ps) == 1
            assert kind[ps[0]] == BLOCK
            assert height[i] == height[ps[0]]
            assert signer[i] == ps[0]
        else:
            saw_block = True
            p0, votes = ps[0], ps[1:]
            assert kind[p0] == BLOCK
            assert height[i] == height[p0] + 1
            assert len(votes) == env.k - 1
            for v in votes:
                assert kind[v] == VOTE and signer[v] == p0
    assert saw_block


def test_progress_tracks_activations(env):
    params = make_params(alpha=0.3, gamma=0.5, max_steps=160)
    stats = env.episode_stats(
        jax.random.PRNGKey(7), params, env.policies["honest"], 192)
    prog = float(stats["episode_progress"])
    acts = float(stats["episode_n_activations"])
    assert prog > 0 and prog / acts > 0.7, (prog, acts)


def test_policies_run_and_terminate(env):
    params = make_params(alpha=0.4, gamma=0.5, max_steps=96)
    for name, policy in env.policies.items():
        traj = env.rollout(jax.random.PRNGKey(5), params, policy, 160)
        done = np.asarray(traj[3])
        assert done.sum() >= 1, name


def test_block_scheme_pays_leader():
    env = SparSSZ(k=4, incentive_scheme="block", max_steps_hint=96)
    params = make_params(alpha=0.3, gamma=0.5, max_steps=64)
    stats = env.episode_stats(
        jax.random.PRNGKey(11), params, env.policies["honest"], 96)
    total = float(stats["episode_reward_attacker"]
                  + stats["episode_reward_defender"])
    prog = float(stats["episode_progress"])
    # k per block == 1 per progress unit on the winning chain
    assert abs(total - prog) <= env.k, (total, prog)
