"""Generic-spec cross-validation via independent simulators.

Mirrors generic_v1/test/test_single_miner_sim.py and
test_network_sim.py: a lone miner's reward equals its progress share,
and honest networks pay each miner ~its compute share — validating the
protocol specs outside the attack model.
"""

import random

import pytest

from cpr_tpu.mdp.generic import get_protocol
from cpr_tpu.mdp.generic.sim import NetworkSim, SingleMinerSim

PROTOS = [
    ("bitcoin", {}),
    ("ethereum", {}),
    ("byzantium", {}),
    ("parallel", {"k": 3}),
    ("ghostdag", {"k": 2}),
]


@pytest.mark.parametrize("name,kw", PROTOS)
def test_single_miner_collects_everything(name, kw):
    sim = SingleMinerSim(get_protocol(name, **kw))
    rew, prg = sim.run(30)
    assert prg >= 30
    # a lone miner's chain contains only its own blocks
    assert rew > 0
    view = sim.view()
    hist = sim.proto.history(view, sim.pstate)
    assert all(view.miner_of(b) == 0 for b in hist[1:])


@pytest.mark.parametrize("name,kw", [("bitcoin", {}), ("parallel", {"k": 3}),
                                     ("ghostdag", {"k": 2})])
def test_network_sim_fair_shares(name, kw):
    """Zero-delay honest network: rewards proportional to compute."""
    weights = [0.5, 0.3, 0.2]

    def select(rng):
        return rng.choices(range(3), weights=weights)[0]

    sim = NetworkSim(get_protocol(name, **kw), n_miners=3,
                     mining_delay=lambda rng: rng.expovariate(1.0),
                     select_miner=select,
                     message_delay=lambda rng: 0.0, seed=1)
    out = sim.run(150)
    total = sum(out["rewards"])
    assert total > 0
    for i, w in enumerate(weights):
        assert abs(out["rewards"][i] / total - w) < 0.10, (i, out)


def test_network_sim_delay_causes_orphans():
    """bitcoin with message delay near the block interval forks often:
    chain height falls behind the block count."""
    sim = NetworkSim(get_protocol("bitcoin"), n_miners=4,
                     mining_delay=lambda rng: rng.expovariate(1.0),
                     select_miner=lambda rng: rng.randrange(4),
                     message_delay=lambda rng: 0.8, seed=3)
    out = sim.run(80)
    assert out["blocks"] - 1 > out["progress"], out


def test_model_and_network_sim_agree_on_honest_share():
    """The attack model under the honest policy and the two-miner
    network sim produce the same attacker share (the reference's
    model-vs-simulator validation, generic_v1/test strategy)."""
    from cpr_tpu.mdp.generic import SingleAgent

    alpha = 0.3
    m = SingleAgent(get_protocol("bitcoin"), alpha=alpha, gamma=0.5,
                    collect_garbage="simple", merge_isomorphic=False,
                    truncate_common_chain=True)
    rng = random.Random(7)
    s = m.start()[0][0]
    rew = prg = 0.0
    for _ in range(3000):
        ts = m.apply(m.honest(s), s)
        t = rng.choices(ts, weights=[t.probability for t in ts])[0]
        s, rew, prg = t.state, rew + t.reward, prg + t.progress

    sim = NetworkSim(get_protocol("bitcoin"), n_miners=2,
                     mining_delay=lambda r: r.expovariate(1.0),
                     select_miner=lambda r: 0 if r.random() < alpha else 1,
                     message_delay=lambda r: 0.0, seed=9)
    out = sim.run(600)
    sim_share = out["rewards"][0] / sum(out["rewards"])
    assert abs(rew / prg - sim_share) < 0.05, (rew / prg, sim_share)
