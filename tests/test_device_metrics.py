"""In-graph device metrics tests: the MetricsSpec cell algebra, the
rollout accumulator threading (chunked == unchunked, zero host syncs
inside the hot loop under `jax.transfer_guard("disallow")`), the
compile_watch retrace pin, VI convergence residuals, the PPO numerical
sentinels with the opt-in checkify mode, and the schema-v2 half of
tools/trace_summary.py.

These are the proof obligations behind docs/OBSERVABILITY.md's claims:
one readback per span, no retraces across same-shape bench reps, and
build-time gating (the off path compiles the pre-metrics program).
"""

import importlib.util
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu import device_metrics, telemetry
from cpr_tpu.device_metrics import MetricsSpec
from cpr_tpu.params import make_params

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


# -- MetricsSpec cell algebra -------------------------------------------------


def test_counter_sums_masks_and_scalars():
    spec = MetricsSpec().counter("c")
    acc = spec.init()
    acc = spec.count(acc, "c", jnp.array([True, False, True]))
    acc = spec.count(acc, "c", 5)
    assert spec.summarize(acc)["c"] == 7
    assert acc["c"].dtype == jnp.int32


def test_stats_masked_observation_and_empty_cell():
    spec = MetricsSpec().stats("s")
    acc = spec.observe(spec.init(), "s", jnp.array([1.0, 2.0, 3.0, 4.0]),
                       where=jnp.array([True, False, True, False]))
    s = spec.summarize(acc)["s"]
    assert s == {"min": 1.0, "max": 3.0, "sum": 4.0, "count": 2.0,
                 "mean": 2.0}
    # a never-observed cell reads as honest Nones, not +-inf
    empty = spec.summarize(spec.init())["s"]
    assert empty["count"] == 0.0
    assert empty["min"] is None and empty["max"] is None \
        and empty["mean"] is None


def test_hist_bins_include_under_and_overflow():
    spec = MetricsSpec().hist("h", [0.0, 10.0, 20.0])
    acc = spec.observe_hist(spec.init(), "h",
                            jnp.array([-5.0, 0.0, 5.0, 10.0, 25.0]))
    h = spec.summarize(acc)["h"]
    assert h["edges"] == [0.0, 10.0, 20.0]
    # [-inf,0) [0,10) [10,20) [20,inf)
    assert h["counts"] == [1, 2, 1, 1]
    masked = spec.observe_hist(spec.init(), "h", jnp.array([5.0, 15.0]),
                               where=jnp.array([True, False]))
    assert spec.summarize(masked)["h"]["counts"] == [0, 1, 0, 0]
    with pytest.raises(AssertionError, match="increasing"):
        MetricsSpec().hist("bad", [1.0, 1.0])


def test_merge_and_on_device_axis_reduction():
    spec = (MetricsSpec().counter("c").stats("s")
            .hist("h", [2.0]))
    a = spec.observe(spec.count(spec.init(), "c", 2), "s", 1.0)
    b = spec.observe(spec.count(spec.init(), "c", 3), "s", 5.0)
    m = spec.summarize(spec.merge(a, b))
    assert m["c"] == 5
    assert m["s"]["min"] == 1.0 and m["s"]["max"] == 5.0 \
        and m["s"]["mean"] == 3.0

    # vmapped lanes reduce back to scalar cells inside one jitted program
    def lane(v):
        acc = spec.count(spec.init(), "c", 1)
        acc = spec.observe(acc, "s", v)
        return spec.observe_hist(acc, "h", v)

    out = jax.jit(lambda vs: spec.merge_axis(jax.vmap(lane)(vs), 0))(
        jnp.array([1.0, 5.0, 3.0]))
    s = spec.summarize(out)
    assert s["c"] == 3
    assert s["s"] == {"min": 1.0, "max": 5.0, "sum": 9.0, "count": 3.0,
                      "mean": 3.0}
    assert s["h"]["counts"] == [1, 2]


def test_enabled_reads_env_var(monkeypatch):
    monkeypatch.delenv(device_metrics.ENV_VAR, raising=False)
    assert not device_metrics.enabled()
    monkeypatch.setenv(device_metrics.ENV_VAR, "1")
    assert device_metrics.enabled()
    monkeypatch.setenv(device_metrics.ENV_VAR, "0")
    assert not device_metrics.enabled()


# -- rollout accumulator threading (envs/base.py) -----------------------------

_N_ENVS, _N_STEPS, _CHUNK = 8, 96, 32


@pytest.fixture(scope="module")
def sm1_metrics_fns():
    """One build of the unchunked and chunked metrics-collecting stats
    fns (module-scoped: the jitted pieces compile once for the battery
    below)."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ

    env = NakamotoSSZ()
    params = make_params(alpha=0.35, gamma=0.5, max_steps=64)
    policy = env.policies["sapirshtein-2016-sm1"]
    keys = jax.random.split(jax.random.PRNGKey(0), _N_ENVS)
    whole = env.make_episode_stats_fn(params, policy, _N_STEPS,
                                      collect_metrics=True)
    chunked = env.make_episode_stats_fn(params, policy, _N_STEPS,
                                        chunk=_CHUNK,
                                        collect_metrics=True)
    return whole, chunked, keys


def test_rollout_metrics_chunked_matches_unchunked(sm1_metrics_fns):
    whole, chunked, keys = sm1_metrics_fns
    stats_w, acc_w = whole(keys)
    stats_c, acc_c = chunked(keys)
    mw = whole.metrics_spec.summarize(acc_w)
    mc = chunked.metrics_spec.summarize(acc_c)
    assert mw["env_steps"] == mc["env_steps"] == _N_ENVS * _N_STEPS
    assert mw["episodes"] == mc["episodes"] > 0
    assert mw["nonfinite_stats"] == mc["nonfinite_stats"] == 0
    assert mw["nonfinite_obs_boundary"] == \
        mc["nonfinite_obs_boundary"] == 0
    # every lane finishes >=1 episode at max_steps=64 in 96 steps, so
    # every lane's mean episode length feeds the stats cell + hist
    assert mw["episode_n_steps"]["count"] == \
        mc["episode_n_steps"]["count"] == _N_ENVS
    assert mw["episode_n_steps"]["sum"] == pytest.approx(
        mc["episode_n_steps"]["sum"], rel=1e-5)
    assert mw["episode_reward_attacker"]["sum"] == pytest.approx(
        mc["episode_reward_attacker"]["sum"], rel=1e-5)
    assert mw["episode_n_steps_hist"]["counts"] == \
        mc["episode_n_steps_hist"]["counts"]
    assert sum(mc["episode_n_steps_hist"]["counts"]) == _N_ENVS
    # the episode stats themselves keep the chunked==unchunked contract
    assert int(stats_w["n_episodes"].sum()) == \
        int(stats_c["n_episodes"].sum())


def test_rollout_with_metrics_folds_per_step_cells():
    """`rollout(with_metrics=True)` keeps the per-step cell set
    (rollout_spec): the caller already pays to materialize the
    trajectory, so the fold over the stacked step axis is free there
    — unlike the stats drivers, whose cells derive from per-lane
    aggregates (episode_stats_spec) to keep the bench overhead <2%."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ

    env = NakamotoSSZ()
    params = make_params(alpha=0.35, gamma=0.5, max_steps=16)
    policy = env.policies["sapirshtein-2016-sm1"]
    traj, acc = env.rollout(jax.random.PRNGKey(0), params, policy, 48,
                            True)
    _, _, reward, done, _ = traj
    s = device_metrics.rollout_spec().summarize(acc)
    assert s["env_steps"] == 48
    assert s["episodes"] == int(done.sum()) > 0
    assert s["reward"]["count"] == 48.0
    assert s["reward"]["sum"] == pytest.approx(float(reward.sum()),
                                               rel=1e-5)
    assert s["nonfinite_obs"] == 0 and s["nonfinite_reward"] == 0
    # per-episode (not per-lane-mean) length distribution here
    assert sum(s["episode_length_hist"]["counts"]) == s["episodes"]
    assert s["episode_length"]["count"] == float(s["episodes"])


def test_chunked_metrics_add_no_transfers_in_hot_loop(sm1_metrics_fns):
    """docs/OBSERVABILITY.md's headline contract: with metrics enabled,
    the whole chunked stats call — init, every chunk, finalize — runs
    without a single host<->device transfer.  The readback
    (`summarize`) happens after the guard, once."""
    _, chunked, keys = sm1_metrics_fns
    jax.block_until_ready(chunked(keys))  # warm: compiles transfer
    with jax.transfer_guard("disallow"):
        stats, acc = chunked(keys)
        jax.block_until_ready((stats, acc))
    summary = chunked.metrics_spec.summarize(acc)
    assert summary["env_steps"] == _N_ENVS * _N_STEPS


def test_rollout_compiles_once_across_same_shape_calls():
    """Retrace pin: repeated same-shape calls of a metrics-collecting
    stats fn hit the executable cache (compile_watch sees exactly one
    compile); a new batch shape costs exactly one more."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ

    env = NakamotoSSZ()
    params = make_params(alpha=0.35, gamma=0.5, max_steps=24)
    policy = env.policies["sapirshtein-2016-sm1"]
    fn = env.make_episode_stats_fn(params, policy, 32,
                                   collect_metrics=True)
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    with telemetry.compile_watch(emit=False) as w:
        jax.block_until_ready(fn(keys))
        jax.block_until_ready(fn(keys))
    assert w.count() == 1, w.events
    assert w.events[0]["compile_s"] >= 0.0
    with telemetry.compile_watch(emit=False) as w2:
        jax.block_until_ready(fn(jax.random.split(
            jax.random.PRNGKey(1), 8)))
    assert w2.count() == 1, w2.events


# -- VI convergence residuals (mdp/explicit.py) -------------------------------


def test_ring_residuals_unrolls_chronologically():
    from cpr_tpu.mdp.explicit import ring_residuals

    r = np.arange(1.0, 6.0, dtype=np.float32)
    np.testing.assert_array_equal(ring_residuals(r, 3), r[:3])
    # sweeps 1..7 into a 5-ring: slot (j-1) % 5 holds delta j
    ring = np.zeros(5, np.float32)
    for j in range(1, 8):
        ring[(j - 1) % 5] = j
    np.testing.assert_array_equal(ring_residuals(ring, 7),
                                  [3.0, 4.0, 5.0, 6.0, 7.0])
    assert len(ring_residuals(np.zeros(0, np.float32), 9)) == 0
    assert len(ring_residuals(r, 0)) == 0


def test_vi_residuals_returned_and_emitted(tmp_path):
    from cpr_tpu.mdp import Compiler, ptmdp
    from cpr_tpu.mdp.models import Fc16BitcoinSM

    c = Compiler(Fc16BitcoinSM(alpha=0.3, gamma=0.5,
                               maximum_fork_length=10))
    tm = ptmdp(c.mdp(), horizon=20).tensor()
    path = tmp_path / "vi.jsonl"
    telemetry.configure(str(path))
    try:
        w = tm.value_iteration(stop_delta=1e-9)
        ch = tm.value_iteration(stop_delta=1e-9, impl="chunked")
    finally:
        telemetry.configure(None)

    rw, rc = w["vi_residuals"], ch["vi_residuals"]
    # the while impl keeps the last min(it, 512) sweeps; the chunked
    # impl keeps all of them (the host already syncs on each chunk)
    assert len(rw) == min(int(w["vi_iter"]), 512)
    assert len(rc) == int(ch["vi_iter"])
    assert rw[-1] <= 1e-9 and rc[-1] <= 1e-9  # ends converged
    assert (rw >= 0).all() and rw[0] > rw[-1]  # contraction, down to 0
    # same Bellman sweeps -> same per-sweep deltas, either impl
    n = min(len(rw), len(rc))
    np.testing.assert_allclose(rc[:n], rw[:n], rtol=1e-5, atol=1e-12)

    with open(path) as f:
        events = [json.loads(line) for line in f]
    vi_events = [e for e in events if e.get("name") == "vi_residuals"]
    assert [e["impl"] for e in vi_events] == ["while", "chunked"]
    for e, res in zip(vi_events, (w, ch)):
        assert e["n_sweeps"] == int(res["vi_iter"])
        assert len(e["residuals"]) == min(e["n_sweeps"], 512)
        assert e["truncated"] == (e["n_sweeps"] > len(e["residuals"]))
        assert e["final_delta"] <= e["stop_delta"] == 1e-9
        missing = [k for k in telemetry.EVENT_FIELDS["vi_residuals"]
                   if k not in e]
        assert not missing


# -- PPO sentinels + checkify (train/ppo.py) ----------------------------------


def _tiny_ppo(env_var_on, monkeypatch, **cfg_kw):
    from cpr_tpu.envs.nakamoto import NakamotoSSZ
    from cpr_tpu.train.ppo import PPOConfig, make_train

    if env_var_on:
        monkeypatch.setenv(device_metrics.ENV_VAR, "1")
    else:
        monkeypatch.delenv(device_metrics.ENV_VAR, raising=False)
    env = NakamotoSSZ()
    params = make_params(alpha=0.45, gamma=0.9, max_steps=32)
    cfg = PPOConfig(n_envs=4, n_steps=16, hidden=(8,), update_epochs=2,
                    n_minibatches=2, **cfg_kw)
    return make_train(env, params, cfg)


def test_ppo_train_step_accumulates_sentinels(monkeypatch):
    init_fn, train_step = _tiny_ppo(True, monkeypatch)
    assert train_step.metrics_spec is not None
    carry, metrics = jax.jit(train_step)(init_fn(jax.random.PRNGKey(0)))
    acc = metrics.pop("device_metrics")
    s = train_step.metrics_spec.summarize(acc)
    assert s["minibatches"] == 4  # update_epochs x n_minibatches
    assert s["nonfinite_advantages"] == 0 and s["nonfinite_loss"] == 0
    assert s["minibatches_skipped"] == 0  # no target_kl -> never gated
    assert s["approx_kl"]["count"] == 4.0
    assert np.isfinite(s["approx_kl"]["mean"])
    # the loss metrics themselves stay host-convertible after the pop
    assert np.isfinite(float(metrics["pg_loss"]))


def test_ppo_off_path_has_no_metrics_key(monkeypatch):
    init_fn, train_step = _tiny_ppo(False, monkeypatch)
    assert train_step.metrics_spec is None
    _, metrics = jax.jit(train_step)(init_fn(jax.random.PRNGKey(0)))
    assert "device_metrics" not in metrics


def test_checkify_gate_off_on_and_error_event(tmp_path, monkeypatch):
    from jax.experimental import checkify

    from cpr_tpu.train.ppo import maybe_checkify

    # off: plain jit passthrough
    monkeypatch.delenv(telemetry.CHECKIFY_ENV_VAR, raising=False)
    f = maybe_checkify(lambda x: x * 2.0)
    assert float(f(jnp.float32(3.0))) == 6.0

    monkeypatch.setenv(telemetry.CHECKIFY_ENV_VAR, "1")
    path = tmp_path / "checkify.jsonl"
    telemetry.configure(str(path))
    try:
        # on + clean program: transparent
        g = maybe_checkify(lambda x: x * 2.0)
        assert float(g(jnp.float32(3.0))) == 6.0
        # on + poisoned program: telemetry event, then the usual raise
        bad = maybe_checkify(lambda x: x / jnp.zeros_like(x))
        with pytest.raises(checkify.JaxRuntimeError, match="zero"):
            bad(jnp.float32(1.0))
    finally:
        telemetry.configure(None)
    with open(path) as f:
        events = [json.loads(line) for line in f]
    (err,) = [e for e in events if e.get("name") == "checkify_error"]
    assert "zero" in err["error"]


# -- trace_summary schema v2 --------------------------------------------------


def _load_trace_summary():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_summary.py")
    spec = importlib.util.spec_from_file_location("trace_summary", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_summary_v2_tables_and_expect(tmp_path, capsys):
    ts = _load_trace_summary()
    path = tmp_path / "v2.jsonl"
    tele = telemetry.Telemetry(str(path))
    with tele.span("measure", env_steps=10):
        pass
    tele.event("compile", fn="run", arg_shapes="[f32[8]]",
               trace_s=0.1, compile_s=0.5)
    tele.event("device_metrics", scope="rollout", metrics={
        "env_steps": 768,
        "reward": {"min": 0.0, "max": 1.0, "sum": 3.0, "count": 6.0,
                   "mean": 0.5},
        "never": {"min": None, "max": None, "sum": 0.0, "count": 0.0,
                  "mean": None},
        "hist": {"edges": [1.0, 2.0], "counts": [0, 1, 2]},
    })
    tele.event("vi_residuals", impl="while", n_sweeps=3,
               residuals=[1.0, 0.1, 0.01], truncated=False)
    tele.event("tpu_outage", reason="watchdog")
    tele.manifest(config={})
    tele.close()

    ts.main(["trace_summary", str(path), "--validate", "--expect",
             "device_metrics,compile,vi_residuals,tpu_outage"])
    out = capsys.readouterr().out
    assert "compiled fn" in out
    assert "device_metrics scope=rollout" in out
    assert "counts=[0, 1, 2]" in out
    assert "vi_residuals impl=while" in out and "n_sweeps=3" in out
    assert '"name": "tpu_outage"' in out  # stays a free-form line

    # a missing expected type fails the artifact
    with pytest.raises(SystemExit) as exc:
        ts.main(["trace_summary", str(path), "--validate",
                 "--expect=no_such_event"])
    assert exc.value.code == 1

    # a typed event missing its declared fields fails validation
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        json.dumps({"kind": "event", "name": "compile"}) + "\n"
        + json.dumps({"kind": "event", "name": "device_metrics",
                      "scope": "x"}) + "\n"
        + json.dumps({"kind": "manifest", "backend": "cpu"}) + "\n")
    events, badlines = ts.read_events(str(bad))
    errors = ts.validate(events, badlines)
    assert any("compile missing" in e for e in errors)
    assert any("device_metrics missing ['metrics']" in e
               for e in errors)
