"""Alternative-gym tests: fc16 closed-form env + generic
Release/Consider/Continue env.

Mirrors gym/rust/test/test_rust.py + test_regenvs.py: env contract,
action codec round-trip, revenue sanity against the closed form.
"""

import gymnasium
import numpy as np
import pytest
from gymnasium.utils.env_checker import check_env

import cpr_tpu.gym  # noqa: F401  (registers ids)
from cpr_tpu.gym.generic_env import (FC16Env, GenericEnv, decode_action,
                                     encode_action)


def test_action_codec_roundtrip():
    """generic/mod.rs:236-279 semantics: Continue at 0, Release below,
    Consider above, saturating at the u8 bound."""
    assert decode_action(0.0) == ("continue", 0)
    for kind in ("release", "consider"):
        for i in (0, 1, 5, 40):
            a = encode_action(kind, i)
            assert -1.0 < a < 1.0
            assert decode_action(a) == (kind, i)
    assert decode_action(-1.0) == ("release", 255)
    assert decode_action(1.0) == ("consider", 255)
    assert encode_action("release", 0) < 0 < encode_action("consider", 0)


def test_fc16_env_contract():
    check_env(FC16Env(alpha=0.3, gamma=0.5, horizon=20),
              skip_render_check=True)


def test_fc16_env_ids_registered():
    for eid in ("FC16SSZwPT-v0", "cpr-generic-v0"):
        assert eid in gymnasium.envs.registry
    env = gymnasium.make("FC16SSZwPT-v0", alpha=0.25)
    obs, _ = env.reset(seed=0)
    assert obs.shape == (3,)


def test_fc16_wait_adopt_policy_earns_alpha():
    """Honest-equivalent play (adopt when behind, override when ahead)
    earns ~alpha of progress across many PT episodes."""
    env = FC16Env(alpha=0.3, gamma=0.5, horizon=30, seed=2)
    total_r = total_p = 0.0
    for ep in range(300):
        obs, _ = env.reset()
        done = False
        while not done:
            a, h = env.state.a, env.state.h
            act = 1 if a > h else (0 if h > a else 3)
            obs, r, done, trunc, info = env.step(act)
            total_r += r
            total_p += info["progress"]
    assert abs(total_r / total_p - 0.3) < 0.04, total_r / total_p


@pytest.mark.parametrize("protocol,kw", [("bitcoin", {}),
                                         ("ghostdag", {"k": 2})])
def test_generic_env_random_rollout(protocol, kw):
    env = GenericEnv(protocol, alpha=0.33, gamma=0.5, horizon=20,
                     seed=3, **kw)
    obs, _ = env.reset(seed=1)
    episodes = 0
    for _ in range(400):
        obs, r, done, trunc, info = env.step(env.action_space.sample())
        assert obs.shape == (5,)
        assert np.isfinite(obs).all() and np.isfinite(r)
        if done:
            episodes += 1
            obs, _ = env.reset()
    assert episodes > 0


def test_generic_env_continue_only_is_honest():
    """Driving with Continue plus honest Consider/Release (via the
    model's honest action encoded through the codec) earns ~alpha."""
    from cpr_tpu.mdp.generic import Consider, Release

    env = GenericEnv("bitcoin", alpha=0.3, gamma=0.5, horizon=25, seed=4)
    total_r = total_p = 0.0
    for ep in range(150):
        obs, _ = env.reset()
        done = False
        while not done:
            h = env.model.honest(env.state)
            if isinstance(h, Release):
                a = encode_action("release", 0)
            elif isinstance(h, Consider):
                a = encode_action("consider", 0)
            else:
                a = encode_action("continue")
            obs, r, done, trunc, info = env.step(np.float32(a))
            total_r += r
            total_p += info["progress"]
    assert abs(total_r / total_p - 0.3) < 0.05, total_r / total_p
