"""Ring-window + ancestry-mask DAG core (cpr_tpu.core.dag).

The ring window is the O(active-set) state representation: slot =
gid mod W, with an env-maintained retirement frontier.  The ancestry
planes replace every while-loop walk with one masked reduction.  Both
must agree exactly with the full-capacity walk-based forms on live
blocks — these tests drive a randomized fork process (mine on either
preference, adopt/override, multi-parent proposals, releases) through
a ring dag and a full dag in lockstep and compare every query.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cpr_tpu.core import dag as D

W = 16  # ring window
BIG = 256  # full-capacity twin
P = 3  # parent row width


def row(*xs):
    r = np.full((P,), -1, np.int32)
    for i, x in enumerate(xs):
        r[i] = x
    return jnp.asarray(r)


def drive(seed, n_steps=70, ring=True):
    """Random fork process; returns (dag, gid_of_slot fn, log).

    Maintains pub/priv preferences; appends blocks/votes; adopts or
    overrides to advance the common ancestor; keeps the ring floor at
    the CA's gid.  All indices handled as slots in the dag under test;
    the log records (gid, parent_gids) so twins can be aligned."""
    rng = np.random.default_rng(seed)
    cap = W if ring else BIG
    dag = D.empty(cap, P, ring=ring, anc_masks=True)
    dag, root = D.append(dag, row(), kind=0, height=0, time=0.0,
                         progress=0.0)
    pub = priv = int(root)
    gid_at = {0: int(root)}  # gid -> slot in THIS dag
    slot_gid = {int(root): 0}
    n = 1
    votes = {0: []}  # gid of block -> vote gids
    pub_g = priv_g = 0

    def slot(g):
        return gid_at[g]

    ca_gid = 0
    for t in range(n_steps):
        r = rng.random()
        time = float(t + 1)
        if n - ca_gid > W - 6:
            # window pressure: resolve the fork (a real policy adopts or
            # overrides; an env would otherwise end the episode on
            # overflow) — forces the CA frontier forward in both twins
            r = 0.85
        if r < 0.55:
            # mine a block on one preference
            on_pub = rng.random() < 0.5
            base_g = pub_g if on_pub else priv_g
            vs = votes.get(base_g, [])[:2]
            parents = row(slot(base_g), *[slot(v) for v in vs])
            h = 1 + int(np.asarray(dag.height[slot(base_g)]))
            dag, idx = D.append(
                dag, parents, kind=0, height=h,
                miner=(0 if on_pub else 1), time=time,
                reward_atk=rng.random(), reward_def=rng.random(),
                vis_d=bool(on_pub))
            g = n
            gid_at[g] = int(idx)
            n += 1
            votes[g] = []
            if on_pub:
                pub_g = g
            else:
                priv_g = g
        elif r < 0.8:
            # vote on a preference tip (kind 1, non-chain append)
            on_pub = rng.random() < 0.5
            base_g = pub_g if on_pub else priv_g
            dag, idx = D.append(
                dag, row(slot(base_g)), kind=1,
                height=int(np.asarray(dag.height[slot(base_g)])),
                time=time, vis_d=bool(on_pub))
            g = n
            gid_at[g] = int(idx)
            n += 1
            votes.setdefault(base_g, []).append(g)
        elif r < 0.9:
            # adopt / override: advances the common ancestor
            if rng.random() < 0.5:
                priv_g = pub_g
            else:
                dag = D.release_masked(dag, jnp.int32(slot(priv_g)), time)
                pub_g = priv_g
        else:
            dag = D.release_masked(dag, jnp.int32(slot(priv_g)), time)
        # retire below the CA like the envs do
        ca = D.common_ancestor_masked(dag, jnp.int32(slot(pub_g)),
                                      jnp.int32(slot(priv_g)))
        assert int(ca) >= 0
        if ring:
            ca_gid = int(dag.gid[int(ca)])
            dag = D.retire_below(dag, jnp.int32(ca_gid))
        else:
            ca_gid = int(ca)  # full mode: slot == gid
        assert not bool(dag.overflow), f"unexpected overflow at t={t}"
    return dag, gid_at, (pub_g, priv_g, n)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_ring_matches_full(seed):
    """Every live-window query agrees between ring and full mode."""
    rdag, rmap, (pub_g, priv_g, n) = drive(seed, ring=True)
    fdag, fmap, (pub_g2, priv_g2, n2) = drive(seed, ring=False)
    assert (pub_g, priv_g, n) == (pub_g2, priv_g2, n2)

    lo = max(0, n - W)
    live = [g for g in range(lo, n)]

    def rmask_gids(mask):
        return {g for g in live if bool(mask[rmap[g]])}

    def fmask_gids(mask):
        return {g for g in live if bool(mask[fmap[g]])}

    # per-slot fields agree on live blocks
    for field in ("kind", "height", "miner", "vis_d", "cum_atk",
                  "cum_def", "born_at"):
        rv = np.asarray(getattr(rdag, field))
        fv = np.asarray(getattr(fdag, field))
        for g in live:
            assert rv[rmap[g]] == fv[fmap[g]], (field, g)

    # exists: ring live set == full's top-W slice
    rex = np.asarray(rdag.exists())
    assert {g for g in live if rex[rmap[g]]} == set(live)

    for g in live:
        r_ch = rmask_gids(np.asarray(D.chain_mask(rdag, jnp.int32(rmap[g]))))
        f_ch = fmask_gids(np.asarray(D.chain_mask(fdag, jnp.int32(fmap[g]))))
        assert r_ch == f_ch, ("chain", g)
        r_cl = rmask_gids(np.asarray(D.closure_mask(rdag, jnp.int32(rmap[g]))))
        f_cl = fmask_gids(np.asarray(D.closure_mask(fdag, jnp.int32(fmap[g]))))
        assert r_cl == f_cl, ("closure", g)

    # CA of the two preferences agrees (by gid)
    rca = int(D.common_ancestor_masked(
        rdag, jnp.int32(rmap[pub_g]), jnp.int32(rmap[priv_g])))
    fca = int(D.common_ancestor_masked(
        fdag, jnp.int32(fmap[pub_g]), jnp.int32(fmap[priv_g])))
    assert int(rdag.gid[rca]) == fca  # full mode: slot == gid


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_masked_queries_match_walks(seed):
    """On a full-capacity dag the masked queries equal the while-loop
    walk forms they replace."""
    dag, gmap, (pub_g, priv_g, n) = drive(seed, ring=False)
    pub, priv = gmap[pub_g], gmap[priv_g]

    # closure_mask == ancestors_mask (the fixpoint BFS)
    for g in range(0, n, 7):
        got = np.asarray(D.closure_mask(dag, jnp.int32(gmap[g])))
        want = np.asarray(D.ancestors_mask(dag, jnp.int32(gmap[g])))
        np.testing.assert_array_equal(got, want)

    # CA == the height-synchronized two-pointer walk
    got = int(D.common_ancestor_masked(dag, jnp.int32(pub), jnp.int32(priv)))
    want = int(D.common_ancestor_by_height(dag, jnp.int32(pub),
                                           jnp.int32(priv)))
    assert got == want

    # chain_first_at_most == block_at_height (blocks only: kind == 0)
    is_block = dag.kind == 0
    for tgt in range(0, int(dag.height[priv]) + 1, 2):
        got = int(D.chain_first_at_most(
            dag, jnp.int32(priv), dag.height, jnp.int32(tgt), is_block))
        want = int(D.block_at_height(
            dag, jnp.int32(priv), jnp.int32(tgt),
            lambda d, i: d.kind[i] == 0))
        assert got == want, tgt

    # release_masked == release_with_ancestors
    got = D.release_masked(dag, jnp.int32(priv), 999.0)
    want = D.release_with_ancestors(dag, jnp.int32(priv), 999.0)
    np.testing.assert_array_equal(np.asarray(got.vis_d),
                                  np.asarray(want.vis_d))
    np.testing.assert_array_equal(np.asarray(got.vis_d_since),
                                  np.asarray(want.vis_d_since))


def test_ring_overflow_on_deep_fork():
    """A fork deeper than the window must flag overflow, not corrupt."""
    dag = D.empty(8, 1, ring=True)
    dag, root = D.append(dag, jnp.array([-1], jnp.int32), height=0)
    tip = root
    # never retire anything: floor stays 0, so the 9th append evicts a
    # live block
    for h in range(1, 9):
        dag, tip = D.append(dag, jnp.array([int(tip)], jnp.int32), height=h)
    assert bool(dag.overflow)


def test_bk_ring_episode_matches_full():
    """A windowed bk env replays a full-capacity episode bit-for-bit:
    same keys, same policy, identical episode stats.  The window (64)
    is chosen WELL BELOW the per-episode append count (~1.2 per step x
    120 steps), so every episode wraps the ring 1-2x — the regime where
    reclaimed slots alias stale rows (the ghost-vote class the
    newer_than guards exist for); bit-equality across 24 streams would
    catch one ghost vote changing one quorum."""
    from cpr_tpu.envs.bk import BkSSZ
    from cpr_tpu.params import make_params

    params = make_params(alpha=0.3, gamma=0.5, max_steps=120)
    keys = jax.random.split(jax.random.PRNGKey(1), 24)
    outs = []
    for env in (BkSSZ(k=4, max_steps_hint=128),
                BkSSZ(k=4, max_steps_hint=128, window=64)):
        assert (env.capacity == 64) == env.ring
        fn = jax.jit(jax.vmap(lambda k: env.episode_stats(
            k, params, env.policies["get-ahead"], 128)))
        outs.append(jax.block_until_ready(fn(keys)))
    full, ring = outs
    for key in sorted(full):
        np.testing.assert_array_equal(
            np.asarray(full[key]), np.asarray(ring[key]), err_msg=key)


def test_tailstorm_ring_episode_matches_full():
    """Windowed tailstorm replays full-capacity episodes bit-for-bit
    (quorum frames, release prefixes, and stale bits all order by age
    key).  Window 48 < ~1.1 appends/step x 96 steps, so every episode
    wraps the ring — exercising slot reuse under the confirming/dup
    newer_than guards."""
    from cpr_tpu.envs.tailstorm import TailstormSSZ
    from cpr_tpu.params import make_params

    params = make_params(alpha=0.3, gamma=0.5, max_steps=96)
    keys = jax.random.split(jax.random.PRNGKey(2), 16)
    outs = []
    for env in (TailstormSSZ(k=4, max_steps_hint=104),
                TailstormSSZ(k=4, max_steps_hint=104, window=48)):
        fn = jax.jit(jax.vmap(lambda k: env.episode_stats(
            k, params, env.policies["get-ahead"], 104)))
        outs.append(jax.block_until_ready(fn(keys)))
    full, ring = outs
    for key in sorted(full):
        np.testing.assert_array_equal(
            np.asarray(full[key]), np.asarray(ring[key]), err_msg=key)


def test_ethereum_ring_episode_matches_full():
    """Windowed ethereum replays full-capacity episodes bit-for-bit;
    window 64 < ~1 append/step x 120 steps, so episodes wrap the ring
    (uncle candidates + race tips under the newer_than guards and the
    uncle-window retirement floor)."""
    from cpr_tpu.envs.ethereum import EthereumSSZ
    from cpr_tpu.params import make_params

    params = make_params(alpha=0.35, gamma=0.5, max_steps=120)
    keys = jax.random.split(jax.random.PRNGKey(3), 16)
    outs = []
    for env in (EthereumSSZ("byzantium", max_steps_hint=128),
                EthereumSSZ("byzantium", max_steps_hint=128, window=64)):
        fn = jax.jit(jax.vmap(lambda k: env.episode_stats(
            k, params, env.policies["fn19"], 128)))
        outs.append(jax.block_until_ready(fn(keys)))
    full, ring = outs
    for key in sorted(full):
        np.testing.assert_array_equal(
            np.asarray(full[key]), np.asarray(ring[key]), err_msg=key)


def test_spar_ring_episode_matches_full():
    """Windowed spar replays full-capacity episodes bit-for-bit; one
    append per step, so window 48 < 96 steps wraps every episode
    (slot reuse under the confirming newer_than guard and the
    first-proposer first_by_age tiebreak)."""
    from cpr_tpu.envs.spar import SparSSZ
    from cpr_tpu.params import make_params

    params = make_params(alpha=0.3, gamma=0.5, max_steps=96)
    keys = jax.random.split(jax.random.PRNGKey(4), 16)
    outs = []
    for env in (SparSSZ(k=4, max_steps_hint=104),
                SparSSZ(k=4, max_steps_hint=104, window=48)):
        fn = jax.jit(jax.vmap(lambda k: env.episode_stats(
            k, params, env.policies["selfish"], 104)))
        outs.append(jax.block_until_ready(fn(keys)))
    full, ring = outs
    for key in sorted(full):
        np.testing.assert_array_equal(
            np.asarray(full[key]), np.asarray(ring[key]), err_msg=key)


def test_stree_ring_episode_matches_full():
    """Windowed stree replays full-capacity episodes bit-for-bit
    (quorum frames and release prefixes order by age key; vote_score's
    fractional-age tiebreak is wrap-invariant).  Window 48 < 96 steps
    at one append per step, so every episode wraps."""
    from cpr_tpu.envs.stree import StreeSSZ
    from cpr_tpu.params import make_params

    params = make_params(alpha=0.3, gamma=0.5, max_steps=96)
    keys = jax.random.split(jax.random.PRNGKey(5), 16)
    outs = []
    for env in (StreeSSZ(k=4, max_steps_hint=104),
                StreeSSZ(k=4, max_steps_hint=104, window=48)):
        fn = jax.jit(jax.vmap(lambda k: env.episode_stats(
            k, params, env.policies["override-catchup"], 104)))
        outs.append(jax.block_until_ready(fn(keys)))
    full, ring = outs
    for key in sorted(full):
        np.testing.assert_array_equal(
            np.asarray(full[key]), np.asarray(ring[key]), err_msg=key)


def test_sdag_ring_episode_matches_full():
    """Windowed sdag replays full-capacity episodes bit-for-bit (the
    block chain rides the chain plane via chain_parent=head; block_lca
    walk vs masked row must agree).  Window 48 < 96 steps at one
    append per step, so every episode wraps."""
    from cpr_tpu.envs.sdag import SdagSSZ
    from cpr_tpu.params import make_params

    params = make_params(alpha=0.3, gamma=0.5, max_steps=96)
    keys = jax.random.split(jax.random.PRNGKey(6), 16)
    outs = []
    for env in (SdagSSZ(k=4, max_steps_hint=104),
                SdagSSZ(k=4, max_steps_hint=104, window=48)):
        fn = jax.jit(jax.vmap(lambda k: env.episode_stats(
            k, params, env.policies["override-catchup"], 104)))
        outs.append(jax.block_until_ready(fn(keys)))
    full, ring = outs
    for key in sorted(full):
        np.testing.assert_array_equal(
            np.asarray(full[key]), np.asarray(ring[key]), err_msg=key)


def test_full_capacity_envs_have_no_planes():
    """Memory-footprint regression: at full capacity (window=None) no
    env state carries the quadratic (B, B) ancestry planes or the ring
    bookkeeping — state stays O(B) per env.  eval_shape: no arrays are
    materialized, so the check is free even at large capacity."""
    from cpr_tpu.envs.bk import BkSSZ
    from cpr_tpu.envs.ethereum import EthereumSSZ
    from cpr_tpu.envs.sdag import SdagSSZ
    from cpr_tpu.envs.spar import SparSSZ
    from cpr_tpu.envs.stree import StreeSSZ
    from cpr_tpu.envs.tailstorm import TailstormSSZ
    from cpr_tpu.params import make_params

    params = make_params(alpha=0.3, gamma=0.5, max_steps=120)
    key = jax.random.PRNGKey(0)
    for env in (BkSSZ(k=4, max_steps_hint=128),
                EthereumSSZ("byzantium", max_steps_hint=128),
                TailstormSSZ(k=4, max_steps_hint=128),
                SparSSZ(k=4, max_steps_hint=128),
                StreeSSZ(k=4, max_steps_hint=128),
                SdagSSZ(k=4, max_steps_hint=128)):
        assert not env.ring and not env.anc_masks
        state, _ = jax.eval_shape(env.reset, key, params)
        name = type(env).__name__
        assert state.dag.chain.shape == (0, 0), name
        assert state.dag.closure.shape == (0, 0), name
        assert state.dag.gid.shape == (0,), name
        # ring mode bounds the planes to the window, not the hint
        wenv = type(env)(**(dict(k=4) if name != "EthereumSSZ"
                            else dict()), max_steps_hint=128, window=64)
        wstate, _ = jax.eval_shape(wenv.reset, key, params)
        W = wenv.capacity
        assert wstate.dag.chain.shape == (W, W), name
        assert wstate.dag.closure.shape == (W, W), name


def test_ring_first_by_age_wraps():
    dag = D.empty(4, 1, ring=True)
    dag, a = D.append(dag, jnp.array([-1], jnp.int32), height=0)
    tip = a
    for h in range(1, 6):
        dag = D.retire_below(dag, dag.n - 2)
        dag, tip = D.append(dag, jnp.array([int(tip)], jnp.int32), height=h)
    assert not bool(dag.overflow)
    # live gids are 2..5 at slots 2,3,0,1; earliest live == gid 2
    mask = dag.exists()
    first = int(D.first_by_age(dag, mask))
    assert int(dag.gid[first]) == 2
