"""Native (C++) generic-MDP compiler vs the Python semantic anchor.

The C++ twin (native/src/generic_compiler.cpp) must reproduce the
Python model EXACTLY: same state count, same transition count, same VI
start value — for every protocol spec.  The Python BFS is the spec;
the native one is how the capstone sizes (BASELINE.md config 5) get
compiled.
"""

import numpy as np
import pytest

from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.generic import SingleAgent, get_protocol
from cpr_tpu.mdp.generic.native import compile_native


def _vi_revenue(mdp, horizon=20):
    tm = ptmdp(mdp, horizon=horizon).tensor()
    vi = tm.value_iteration(stop_delta=1e-9)
    prog = tm.start_value(vi["vi_progress"])
    return float(tm.start_value(vi["vi_value"]) / prog)


CASES = [
    ("bitcoin", {}, 0),
    ("ghostdag", {"k": 2}, 2),
    ("parallel", {"k": 2}, 2),
    ("ethereum", {"h": 3}, 3),
    ("byzantium", {"h": 3}, 3),
]


@pytest.mark.parametrize("proto,kw,k", CASES,
                         ids=[c[0] for c in CASES])
def test_native_matches_python_compiler(proto, kw, k):
    py = Compiler(SingleAgent(
        get_protocol(proto, **kw), alpha=0.33, gamma=0.5,
        collect_garbage="simple", merge_isomorphic=True,
        truncate_common_chain=True, dag_size_cutoff=5)).mdp()
    nat = compile_native(proto, k=k, alpha=0.33, gamma=0.5,
                         collect_garbage="simple", dag_size_cutoff=5)
    assert (nat.n_states, nat.n_transitions) == \
        (py.n_states, py.n_transitions)
    assert abs(_vi_revenue(nat) - _vi_revenue(py)) < 1e-9


def test_native_flag_variants_match_python():
    """Every non-default flag path agrees with the Python model too
    (one variant per entry below; extend the tuple, not a new test)."""
    for flags in (dict(loop_honest=True, truncate_common_chain=False),
                  dict(collect_garbage="judge"),
                  dict(force_consider_own=True),
                  dict(reward_common_chain=True),
                  # height cutoff alone does not bound the space (honest
                  # play keeps mining); pair it with the dag cutoff so
                  # the height trigger binds first
                  dict(traditional_height_cutoff=3)):
        base = dict(alpha=0.3, gamma=0.5, collect_garbage="simple",
                    merge_isomorphic=True, truncate_common_chain=True,
                    dag_size_cutoff=5)
        base.update(flags)
        py = Compiler(SingleAgent(get_protocol("bitcoin"), **base)).mdp()
        nat = compile_native("bitcoin", k=0, **base)
        assert (nat.n_states, nat.n_transitions) == \
            (py.n_states, py.n_transitions), flags
        assert abs(_vi_revenue(nat) - _vi_revenue(py)) < 1e-9, flags


def test_native_rejects_unknown_protocol():
    with pytest.raises(RuntimeError, match="unknown protocol"):
        compile_native("nonsense", k=0, alpha=0.3, gamma=0.5,
                       dag_size_cutoff=5)


def test_native_rejects_unbounded_or_oversized():
    with pytest.raises(RuntimeError, match="unbounded"):
        compile_native("bitcoin", k=0, alpha=0.3, gamma=0.5)
    # the MAXN=20 bitmask capacity (generic_compiler.cpp:41) must
    # surface as a clear Python-level error naming the bound
    with pytest.raises(RuntimeError,
                       match=r"max 16.*MAXN=20.*Python compiler"):
        compile_native("bitcoin", k=0, alpha=0.3, gamma=0.5,
                       dag_size_cutoff=30)
    # cutoff 16 (the max) passes validation — full enumeration at 16 is
    # too big for the test budget, so cap states and expect the cap
    # error, not the capacity error
    with pytest.raises(RuntimeError, match="state cap"):
        compile_native("bitcoin", k=0, alpha=0.3, gamma=0.5,
                       dag_size_cutoff=16, max_states=2_000)


def test_native_state_cap():
    with pytest.raises(RuntimeError, match="state cap"):
        compile_native("ghostdag", k=2, alpha=0.33, gamma=0.5,
                       collect_garbage="simple", dag_size_cutoff=6,
                       max_states=1000)


@pytest.mark.slow
def test_ghostdag_capstone_large_sharded_vi():
    """BASELINE.md config 5 at scale: a six-figure-transition GhostDAG
    table from the native compiler, solved by the mesh-sharded VI, equal
    to the single-device solve."""
    from cpr_tpu.parallel import default_mesh, sharded_value_iteration

    mdp = compile_native("ghostdag", k=2, alpha=0.33, gamma=0.5,
                         collect_garbage="simple", dag_size_cutoff=7)
    assert mdp.n_transitions > 300_000
    tm = ptmdp(mdp, horizon=30).tensor()
    single = tm.value_iteration(stop_delta=1e-5)
    sharded = sharded_value_iteration(tm, default_mesh(), stop_delta=1e-5)
    np.testing.assert_allclose(
        np.asarray(sharded["vi_value"]), np.asarray(single["vi_value"]),
        rtol=1e-5, atol=1e-6)


def test_native_rejects_invalid_flag_combinations():
    """The anchor's constructor validation (model.py:97-102) holds
    natively too."""
    with pytest.raises(RuntimeError, match="either truncate"):
        compile_native("bitcoin", k=0, alpha=0.3, gamma=0.5,
                       dag_size_cutoff=5, loop_honest=True)
    with pytest.raises(RuntimeError, match="requires truncate"):
        compile_native("bitcoin", k=0, alpha=0.3, gamma=0.5,
                       dag_size_cutoff=5, reward_common_chain=True,
                       truncate_common_chain=False)


@pytest.mark.slow
def test_native_parity_randomized_combinations():
    """Fuzz-lite: random (protocol, alpha, gamma, flags) combinations at
    small cutoffs must match the Python anchor exactly — broad coverage
    of flag interactions the curated variants miss."""
    import random

    rng = random.Random(7)

    def rows(m):
        cols = m.arrays()
        return sorted(zip(*(np.asarray(c).tolist() for c in cols)))

    protos = [("bitcoin", {}, 0), ("ghostdag", {"k": 2}, 2),
              ("parallel", {"k": 2}, 2), ("ethereum", {"h": 2}, 2),
              ("byzantium", {"h": 2}, 2)]
    for trial in range(12):
        proto, kw, k = rng.choice(protos)
        alpha = rng.choice((0.2, 0.33, 0.45))
        gamma = rng.choice((0.0, 0.5, 1.0))
        flags = dict(
            collect_garbage=rng.choice(("simple", "judge")),
            merge_isomorphic=rng.random() < 0.7,
            force_consider_own=rng.random() < 0.3,
            # cutoff 4 keeps the PYTHON anchor fast (judge-GC walks the
            # full delivery per state); scale parity is covered by the
            # curated cutoff-5/6 tests
            dag_size_cutoff=4,
        )
        if rng.random() < 0.3 and proto == "bitcoin":
            # loop_honest closes the state space only for linear-chain
            # protocols (see SingleAgent docstring); elsewhere the BFS
            # is unbounded and both compilers would grind forever
            flags.update(truncate_common_chain=False, loop_honest=True)
        elif rng.random() < 0.3:
            flags.update(reward_common_chain=True)
        if rng.random() < 0.3:
            # height cutoff alone is unbounded (honest mining keeps
            # going); layered on the dag cutoff it binds first
            flags.update(traditional_height_cutoff=3)
        py = Compiler(SingleAgent(get_protocol(proto, **kw), alpha=alpha,
                                  gamma=gamma, **flags)).mdp()
        nat = compile_native(proto, k=k, alpha=alpha, gamma=gamma, **flags)
        assert (nat.n_states, nat.n_transitions) == \
            (py.n_states, py.n_transitions), (trial, proto, flags)
        # transition-content equality without per-shape VI compiles:
        # sorted COO rows and the start distribution must match exactly
        assert rows(py) == rows(nat), (trial, proto, flags)
        assert py.start == nat.start, (trial, proto, flags)
