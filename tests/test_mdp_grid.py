"""Grid-batched MDP solving tests (docs/MDP.md): the monomial
parameter tracer, parametric compile parity against fresh per-point
compiles (Python BFS and native C++ paths), the parametric PTO
transform, grid value iteration's bit-identity contract against solo
solves (unsharded, mesh-sharded, and across a kill+resume), the
content-fingerprint solve cache, the v10 `mdp_solve` telemetry event,
and the sparse check()/check_dense() oracle pair behind it all."""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax
from cpr_tpu import telemetry
from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.explicit import MDP
from cpr_tpu.mdp.grid import (
    Param,
    ParamError,
    check_revalue_parity,
    compile_protocol,
    grid_value_iteration,
    param_pair,
    param_ptmdp,
    parametric_compile_native,
    solve_grid_cached,
)
from cpr_tpu.mdp.models import Aft20BitcoinSM, Fc16BitcoinSM
from cpr_tpu.resilience import FAULT_ENV_VAR, InjectedKill

MFL = 6           # battery fork-length small enough for fast VI
HORIZON = 30
POINTS = [(0.2, 0.3), (0.33, 0.5), (0.45, 0.9)]


@pytest.fixture(scope="module")
def fc16_pm():
    return compile_protocol("fc16", cutoff=MFL)


@pytest.fixture(scope="module")
def fc16_pt(fc16_pm):
    return param_ptmdp(fc16_pm, horizon=HORIZON)


def revalued_mdp(pm, a, g):
    """A plain MDP over the SAME revalued probability column the grid
    solves (fresh compiles differ by up to 1 ulp of float association,
    so bit-level comparisons must share the column)."""
    src, act, dst, _, reward, progress = pm.mdp.arrays()
    return MDP(n_states=pm.mdp.n_states, n_actions=pm.mdp.n_actions,
               start=dict(pm.mdp.start), src=src, act=act, dst=dst,
               prob=pm.revalue(a, g), reward=reward, progress=progress)


# ---------------------------------------------------------------- tracer


def test_param_tracer_algebra():
    a, g = param_pair()
    p = a * g * (1 - a)
    assert isinstance(p, Param)
    assert p.expo == (1, 1, 1, 0) and p.coef == 1.0
    # complements map onto the paired exponent slots
    q = (1 - g) * (1 - g)
    assert q.expo == (0, 0, 0, 2)
    # numeric coefficients scale coef, never exponents
    r = 0.5 * a * 2.0
    assert r.expo == (1, 0, 0, 0) and r.coef == 1.0
    # float() recovers the probe evaluation exactly
    assert float(p) == pytest.approx(
        float(a) * float(g) * (1 - float(a)), rel=0, abs=0)
    # comparisons and equality work by probe value / structure
    assert a < 0.5 and a * g < a
    assert a * g == g * a
    # addition exits the monomial ring to a plain float (validation
    # sums only)
    s = a + (1 - a)
    assert isinstance(s, float) and s == pytest.approx(1.0)


def test_param_tracer_rejects_non_monomials():
    a, g = param_pair()
    with pytest.raises(ParamError):
        a - 1  # noqa: B018 — only (1 - x) complements are monomial
    with pytest.raises(ParamError):
        1 - a * g  # complement of a product is not a monomial
    with pytest.raises(ParamError):
        1 - 2 * a  # complement needs a coefficient-1 operand
    with pytest.raises(TypeError):
        a / g  # noqa: B018 — division is not supported at all


# ------------------------------------------------- parametric compile


def test_revalue_parity_fc16_aft20():
    for proto, cls in (("fc16", Fc16BitcoinSM), ("aft20", Aft20BitcoinSM)):
        pm = compile_protocol(proto, cutoff=MFL)
        n = check_revalue_parity(
            pm, lambda a, g, cls=cls: cls(alpha=a, gamma=g,
                                          maximum_fork_length=MFL),
            POINTS)
        assert n == len(POINTS)


def test_revalue_parity_generic_python():
    from cpr_tpu.mdp.generic import SingleAgent, get_protocol

    for proto, kw in (("bitcoin", {}), ("ghostdag", {"k": 2})):
        pm = compile_protocol(proto, cutoff=5, native=False, **kw)

        def fresh(a, g, proto=proto, kw=kw):
            return SingleAgent(get_protocol(proto, **kw), alpha=a,
                               gamma=g, collect_garbage="simple",
                               merge_isomorphic=True,
                               truncate_common_chain=True,
                               dag_size_cutoff=5)

        assert check_revalue_parity(pm, fresh, POINTS) == len(POINTS)


def test_native_exponent_recovery_matches_python():
    """The native path recovers (i, j, k, l) from the two-probe float
    table: the resulting ParamMDP must revalue onto the Python BFS
    compile's columns at every probe point."""
    py = compile_protocol("bitcoin", cutoff=5, native=False)
    nat = parametric_compile_native("bitcoin", collect_garbage="simple",
                                    dag_size_cutoff=5)
    assert nat.n_states == py.n_states
    assert nat.n_transitions == py.n_transitions
    for a, g in POINTS:
        np.testing.assert_allclose(nat.revalue(a, g), py.revalue(a, g),
                                   rtol=1e-9, atol=0)


def test_param_ptmdp_matches_explicit_ptmdp(fc16_pm, fc16_pt):
    a, g = 0.33, 0.6
    oracle = ptmdp(revalued_mdp(fc16_pm, a, g), horizon=HORIZON)
    assert fc16_pt.n_transitions == oracle.n_transitions
    assert fc16_pt.mdp.start == oracle.start
    np.testing.assert_allclose(fc16_pt.revalue(a, g),
                               np.asarray(oracle.prob, np.float64),
                               rtol=1e-12, atol=0)


def test_fingerprint_tracks_structure_not_probes(fc16_pm):
    fp = fc16_pm.fingerprint()
    assert fp == compile_protocol("fc16", cutoff=MFL).fingerprint()
    assert fp != compile_protocol("fc16", cutoff=MFL + 1).fingerprint()


# ---------------------------------------------------------- grid solve


def test_grid_vi_bit_identical_to_solo(fc16_pt):
    alphas, gammas = (0.25, 0.4), (0.3, 0.8)
    vi = grid_value_iteration(fc16_pt, alphas, gammas, stop_delta=1e-6)
    assert vi["grid_converged"].all()
    for gi, (a, g) in enumerate(vi["grid_points"]):
        tm = revalued_mdp(fc16_pt, a, g).tensor()
        solo = tm.value_iteration(impl="chunked", stop_delta=1e-6)
        # the contract: per-point fixpoints are the SOLO fixpoints,
        # bit for bit — convergence bit-freezing never perturbs them
        np.testing.assert_array_equal(vi["grid_value"][gi],
                                      solo["vi_value"])
        np.testing.assert_array_equal(vi["grid_progress"][gi],
                                      solo["vi_progress"])
        np.testing.assert_array_equal(vi["grid_policy"][gi],
                                      solo["vi_policy"])
        assert int(vi["grid_iter"][gi]) == int(solo["vi_iter"])
        # revenue weights by the point's OWN revalued start vector
        # (fc16 starts are alpha-dependent, unlike the probe start
        # baked into revalued_mdp)
        start = fc16_pt.start_vector(a, g)
        rev = ((start * solo["vi_value"]).sum()
               / (start * solo["vi_progress"]).sum())
        assert vi["grid_revenue"][gi] == pytest.approx(float(rev),
                                                       rel=1e-12)


def test_grid_vi_sharded_matches_unsharded(fc16_pt):
    from cpr_tpu.parallel import default_mesh

    mesh = default_mesh(devices=jax.devices()[:4])
    alphas, gammas = (0.25, 0.4), (0.3, 0.8)  # G=4 over 4 devices
    plain = grid_value_iteration(fc16_pt, alphas, gammas,
                                 stop_delta=1e-6)
    shard = grid_value_iteration(fc16_pt, alphas, gammas,
                                 stop_delta=1e-6, mesh=mesh)
    for key in ("grid_value", "grid_progress", "grid_policy",
                "grid_iter", "grid_revenue"):
        np.testing.assert_array_equal(plain[key], shard[key])
    assert plain["vi_iter"] == shard["vi_iter"]


def test_grid_vi_rejects_uneven_shards(fc16_pt):
    from cpr_tpu.parallel import default_mesh

    mesh = default_mesh(devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="grid points"):
        grid_value_iteration(fc16_pt, (0.25, 0.3, 0.4), (0.5,),
                             stop_delta=1e-6, mesh=mesh)


def test_grid_vi_kill_resume_bit_identical(fc16_pt, tmp_path,
                                           monkeypatch):
    """A crash mid-grid-solve leaves a checkpoint; the resumed run
    lands on exactly the uninterrupted fixpoints and cleans up."""
    alphas, gammas = (0.25, 0.4), (0.5,)
    clean = grid_value_iteration(fc16_pt, alphas, gammas,
                                 stop_delta=1e-6, chunk=32)
    ck = tmp_path / "grid_vi.npz"
    monkeypatch.setenv(FAULT_ENV_VAR, "kill@vi_chunk=3")
    with pytest.raises(InjectedKill):
        grid_value_iteration(fc16_pt, alphas, gammas, stop_delta=1e-6,
                             chunk=32, checkpoint_path=str(ck))
    assert ck.exists(), "checkpoint must survive the crash"
    monkeypatch.delenv(FAULT_ENV_VAR)
    resumed = grid_value_iteration(fc16_pt, alphas, gammas,
                                   stop_delta=1e-6, chunk=32,
                                   checkpoint_path=str(ck))
    for key in ("grid_value", "grid_progress", "grid_policy",
                "grid_iter"):
        np.testing.assert_array_equal(clean[key], resumed[key])
    assert clean["vi_iter"] == resumed["vi_iter"]
    assert not ck.exists(), "checkpoint is crash scratch, not artifact"


def test_solve_grid_cached(tmp_path, monkeypatch):
    monkeypatch.setenv("CPR_MDP_CACHE", str(tmp_path))
    kw = dict(cutoff=MFL, alphas=(0.25, 0.4), gammas=(0.5,),
              horizon=HORIZON, stop_delta=1e-6)
    miss = solve_grid_cached("fc16", **kw)
    assert miss["cached"] is False and all(miss["converged"])
    hit = solve_grid_cached("fc16", **kw)
    assert hit["cached"] is True
    assert hit["revenue"] == miss["revenue"]
    assert hit["fingerprint"] == miss["fingerprint"]
    # the policy variant is a distinct cache entry carrying the tables
    pol = solve_grid_cached("fc16", include_policy=True, **kw)
    assert pol["cached"] is False and "policy" in pol
    assert pol["revenue"] == pytest.approx(miss["revenue"])


# -------------------------------------------------------- observability


def _load_trace_summary():
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_summary.py")
    spec = importlib.util.spec_from_file_location("trace_summary", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_mdp_solve_event_validates(fc16_pt, tmp_path):
    trace = tmp_path / "mdp.jsonl"
    telemetry.configure(str(trace))
    try:
        tele = telemetry.current()
        tele.manifest(config={"role": "test-mdp-grid"})
        grid_value_iteration(fc16_pt, (0.25, 0.4), (0.5,),
                             stop_delta=1e-6, protocol="fc16",
                             cutoff=MFL)
    finally:
        telemetry.configure(None)
    ts = _load_trace_summary()
    events, bad = ts.read_events(str(trace))
    assert ts.validate(events, bad, expect=("mdp_solve",)) == []
    (ev,) = [e for e in events if e.get("name") == "mdp_solve"]
    assert ev["protocol"] == "fc16" and ev["cutoff"] == MFL
    assert ev["grid"] == [2, 1] and ev["converged"] == 2
    assert ev["points_per_sec"] > 0


def test_mdp_solve_event_banks_in_ledger(fc16_pt, tmp_path):
    from cpr_tpu.perf.ledger import Ledger

    trace = tmp_path / "mdp.jsonl"
    telemetry.configure(str(trace))
    try:
        telemetry.current().manifest(config={"devices": 1})
        grid_value_iteration(fc16_pt, (0.25, 0.4), (0.5,),
                             stop_delta=1e-6, protocol="fc16",
                             cutoff=MFL)
    finally:
        telemetry.configure(None)
    led = Ledger(str(tmp_path / "ledger.jsonl"))
    assert led.ingest_trace(str(trace)) >= 2
    by_metric = {r["metric"]: r for r in led.records()}
    pps = by_metric["mdp_grid_points_per_sec"]
    assert pps["unit"] == "grid-points/sec" and pps["value"] > 0
    assert pps["config"]["cfg_protocol"] == "fc16"
    assert pps["config"]["cfg_grid"] == "2x1"
    assert pps["config"]["cfg_devices"] == 1
    lat = by_metric["mdp_grid_point_solve_s"]
    assert lat["unit"] == "seconds" and lat["value"] > 0


# -------------------------------------------- check() + arrays() cache


def test_check_sparse_matches_dense_oracle(fc16_pm):
    good = fc16_pm.mdp
    assert good.check() and good.check_dense()

    bad_prob = MDP()
    bad_prob.add_transition(0, 0, 1, probability=0.6, reward=0.0,
                            progress=0.0)
    bad_prob.add_transition(1, 0, 0, probability=1.0, reward=0.0,
                            progress=0.0)
    bad_prob.start = {0: 1.0}
    with pytest.raises(AssertionError, match="sum to 1"):
        bad_prob.check()
    with pytest.raises(AssertionError, match="sum to 1"):
        bad_prob.check_dense()

    gap = MDP()
    gap.add_transition(0, 0, 1, probability=1.0, reward=0.0,
                       progress=0.0)
    gap.add_transition(0, 2, 1, probability=1.0, reward=0.0,
                       progress=0.0)  # action 1 missing at state 0
    gap.add_transition(1, 0, 0, probability=1.0, reward=0.0,
                       progress=0.0)
    gap.start = {0: 1.0}
    with pytest.raises(AssertionError, match="non-contiguous"):
        gap.check()
    with pytest.raises(AssertionError, match="non-contiguous"):
        gap.check_dense()


def test_arrays_cache_identity_and_invalidation():
    m = MDP()
    m.add_transition(0, 0, 1, probability=1.0, reward=1.0, progress=1.0)
    first = m.arrays()
    assert m.arrays() is first  # cached tuple, no rebuild
    m.add_transition(1, 0, 0, probability=1.0, reward=0.0, progress=1.0)
    second = m.arrays()
    assert second is not first and len(second[0]) == 2


# ------------------------------------------------------------- adoption


def test_measure_rows_grid_matches_serial(tmp_path, monkeypatch):
    from cpr_tpu.experiments.measure_mdp import (measure_rows,
                                                 measure_rows_grid)

    alphas, gamma = (0.25, 0.4), 0.5
    battery = [(f"fc16-{a}",
                lambda a=a: Fc16BitcoinSM(alpha=a, gamma=gamma,
                                          maximum_fork_length=MFL))
               for a in alphas]
    serial = measure_rows(battery, horizon=HORIZON)
    grid = measure_rows_grid([("fc16", MFL, {}, "fc16")], alphas=alphas,
                             gamma=gamma, horizon=HORIZON)
    assert [r["model"] for r in grid] == [r["model"] for r in serial]
    for gr, sr in zip(grid, serial):
        assert gr["n_states"] == sr["n_states"]
        assert gr["n_transitions"] == sr["n_transitions"]
        assert gr["revenue"] == pytest.approx(sr["revenue"], abs=5e-6)


def test_break_even_exact_monotone_in_gamma(tmp_path, monkeypatch):
    from cpr_tpu.experiments.break_even import (break_even_exact,
                                                exact_revenue_curve)

    monkeypatch.setenv("CPR_MDP_CACHE", str(tmp_path))
    curve = exact_revenue_curve("fc16", gamma=0.5, cutoff=MFL,
                                alphas=(0.2, 0.3, 0.4), horizon=HORIZON)
    assert curve == sorted(curve)  # revenue rises with attacker share
    kw = dict(cutoff=MFL, support=(0.1, 0.45), grid=5, horizon=HORIZON)
    be_lo = break_even_exact("fc16", gamma=0.2, **kw)
    be_hi = break_even_exact("fc16", gamma=0.9, **kw)
    assert 0.1 <= be_hi <= be_lo <= 0.45  # better comms, easier attack


def test_serve_mdp_solve_grid_dispatch(tmp_path, monkeypatch):
    """The serve op is a thin blocking wrapper over solve_grid_cached:
    exercise the handler directly (the full socket path is covered by
    `make mdp-smoke`)."""
    import asyncio

    from cpr_tpu.serve.server import ServeServer

    monkeypatch.setenv("CPR_MDP_CACHE", str(tmp_path))
    srv = ServeServer.__new__(ServeServer)

    async def run():
        return srv._mdp_solve_grid(dict(
            protocol="fc16", cutoff=MFL, alphas=[0.25, 0.4],
            gammas=[0.5], horizon=HORIZON))

    out = asyncio.run(run())
    assert out["ok"] and out["cached"] is False
    assert len(out["revenue"]) == 2 and all(out["converged"])
