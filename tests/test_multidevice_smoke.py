"""Virtual multi-device CPU smoke in a CHILD process (ROADMAP item 1).

The in-suite multichip tests inherit the parent's 8-device virtual
mesh; this one proves the CI story works from a cold start — a fresh
process, `XLA_FLAGS=--xla_force_host_platform_device_count=4`, CPU
forced programmatically (the axon PJRT plugin ignores JAX_PLATFORMS —
the bench run_one lesson), 4 devices actually present, and the
mesh-sharded value iteration agreeing with the single-device solve.
"""

import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_CHILD = textwrap.dedent("""
    import json

    import jax

    # programmatic force: JAX_PLATFORMS alone does not stop the axon
    # plugin from claiming the chip (see bench.run_one)
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from jax.sharding import Mesh

    from cpr_tpu.mdp import Compiler, ptmdp
    from cpr_tpu.mdp.models import Fc16BitcoinSM
    from cpr_tpu.parallel import sharded_value_iteration

    devs = jax.devices()
    c = Compiler(Fc16BitcoinSM(alpha=0.35, gamma=0.5,
                               maximum_fork_length=5))
    tm = ptmdp(c.mdp(), horizon=12).tensor()
    mesh = Mesh(np.asarray(devs), ("d",))
    vi = sharded_value_iteration(tm, mesh, stop_delta=1e-6,
                                 impl="chunked", chunk=8)
    single = tm.value_iteration(stop_delta=1e-6)
    print(json.dumps({
        "platform": devs[0].platform,
        "device_count": len(devs),
        "sharded": float(tm.start_value(vi["vi_value"])),
        "single": float(tm.start_value(single["vi_value"])),
    }))
""")


def _run_child(script):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4 "
                  "--xla_backend_optimization_level=0",
    )
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=480)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.splitlines()[-1])


def test_four_virtual_devices_sharded_vi_parity():
    out = _run_child(_CHILD)
    assert out["platform"] == "cpu"
    assert out["device_count"] == 4, out
    assert abs(out["sharded"] - out["single"]) < 1e-4, out


# the sharded resident lane stepper from the same cold start: episode
# aggregates out of a burst over mesh-sharded lanes must be
# BIT-identical to the single-device engine — the multichip-smoke
# acceptance check, small enough for the fast tier
_LANES_CHILD = textwrap.dedent("""
    import json

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from cpr_tpu.envs import registry
    from cpr_tpu.parallel import default_mesh
    from cpr_tpu.params import make_params
    from cpr_tpu.serve.engine import ResidentEngine

    devs = jax.devices()
    env = registry.get_sized("nakamoto", 16)
    params = make_params(alpha=0.25, gamma=0.5, max_steps=16)
    engines = {
        1: ResidentEngine(env, params, n_lanes=8, burst=16),
        4: ResidentEngine(env, params, n_lanes=8, burst=16,
                          mesh=default_mesh(devices=devs[:4])),
    }
    regs = {}
    for n, eng in engines.items():
        eng.start()
        eng.splice({lane: 10 + lane for lane in range(8)})
        pid = eng.policy_ids["honest"]
        out = eng.burst_run({lane: pid for lane in range(8)})
        regs[n] = {k: np.asarray(v).tolist() for k, v in out.items()}
    print(json.dumps({
        "platform": devs[0].platform,
        "device_count": len(devs),
        "report_devices": {str(n): e.report()["n_devices"]
                           for n, e in engines.items()},
        "identical": regs[1] == regs[4],
    }))
""")


def test_four_virtual_devices_lane_burst_parity():
    out = _run_child(_LANES_CHILD)
    assert out["platform"] == "cpu"
    assert out["device_count"] == 4, out
    assert out["report_devices"] == {"1": 1, "4": 4}, out
    assert out["identical"], "sharded burst registers diverged"
