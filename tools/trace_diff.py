"""Span-level regression diff between two telemetry runs.

The attribution half of the perf plane: the ledger + gate say THAT a
number regressed (`perf_gate` FAIL/WARN, schema v15 carrying the
candidate and baseline run ids); this tool says WHERE.  It aligns the
two runs' span trees by path and computes, per span path:

    d_total   candidate total wall seconds minus baseline
    d_self    same, on SELF time (total minus direct children) — the
              ranking key, so a slow leaf is named instead of every
              ancestor that merely contains it
    d_call    per-call mean delta (calls can differ between runs)
    d_count   call-count delta

and ranks culprit paths by their self-time contribution to the
end-to-end delta (the sum over root spans).  Supporting tables cover
the other things a regression hides in: per-span counter rates
(steps/sec and friends), `compile` events (retrace count + compile
seconds per fn), `device_metrics` numeric cells, the serve report's
per-family latency quantiles, and v15 `memory` watermark peaks per
scope.

Each side is either a telemetry JSONL path (repeatable via commas) or
a run id resolved through the run archive (cpr_tpu.perf.archive —
every archived telemetry stream of the run is merged, so a
supervised server + client pair diffs as one run).  `perf_report
--attribute` drives this module directly to chase a gate FAIL into a
named culprit table.

Usage: python tools/trace_diff.py BASELINE CANDIDATE
           [--archive DIR] [--top N] [--json]

Exit codes: 0 = diffed, 1 = no overlapping span paths, 2 = usage/IO.
"""

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def read_events(paths):
    events = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except ValueError:
                    continue
    return events


def resolve_side(spec, archive_root=None):
    """One side of the diff -> (label, [stream paths]).  A spec that
    names existing files (comma-separated) is used verbatim; anything
    else is a run id looked up in the archive."""
    parts = [p for p in str(spec).split(",") if p]
    if parts and all(os.path.exists(p) for p in parts):
        return spec, parts
    from cpr_tpu.perf import archive
    rec = archive.load_run(spec, root=archive_root)
    if rec is None:
        raise SystemExit(
            f"trace_diff: {spec!r} is neither a stream path nor a "
            f"run id in archive {archive.archive_dir(archive_root)!r}")
    streams = archive.run_streams(rec)
    if not streams:
        raise SystemExit(
            f"trace_diff: archived run {spec!r} has no telemetry "
            f"stream on disk")
    return spec, streams


def _children(path, all_paths):
    """Direct children of `path` in the span tree (paths are
    '/'-joined; a child extends the parent by exactly one segment)."""
    prefix = path + "/"
    depth = path.count("/") + 1
    return [p for p in all_paths
            if p.startswith(prefix) and p.count("/") == depth]


def collect(events):
    """Fold one run's events into the comparable aggregate."""
    spans = defaultdict(lambda: {"calls": 0, "total_s": 0.0})
    counters = defaultdict(lambda: [0.0, 0.0])  # (path, k) -> [n, dur]
    for e in events:
        if e.get("kind") != "span":
            continue
        path = e.get("path") or e.get("name") or "?"
        s = spans[path]
        s["calls"] += 1
        s["total_s"] += e.get("dur_s") or 0.0
        for k, v in (e.get("counters") or {}).items():
            c = counters[(path, k)]
            c[0] += v
            c[1] += e.get("dur_s") or 0.0
    paths = set(spans)
    for path, s in spans.items():
        child_total = sum(spans[c]["total_s"]
                          for c in _children(path, paths))
        # clamped: overlapping/async children can sum past the parent
        s["self_s"] = max(0.0, s["total_s"] - child_total)
    roots = [p for p in paths if "/" not in p]
    compiles = defaultdict(lambda: {"count": 0, "compile_s": 0.0})
    device_cells = {}
    latency = {}
    memory = {}
    for e in events:
        if e.get("kind") != "event":
            continue
        name = e.get("name")
        if name == "compile":
            c = compiles[e.get("fn") or "?"]
            c["count"] += 1
            c["compile_s"] += e.get("compile_s") or 0.0
        elif name == "device_metrics":
            scope = e.get("scope") or "?"
            for k, v in (e.get("metrics") or {}).items():
                cell = f"{scope}.{k}"
                if isinstance(v, (int, float)):
                    device_cells[cell] = float(v)
                elif isinstance(v, dict) and isinstance(
                        v.get("mean"), (int, float)):
                    device_cells[cell] = float(v["mean"])
        elif name == "serve" and e.get("action") == "report":
            for fam, q in ((e.get("detail") or {}).get("latency")
                           or {}).items():
                if isinstance(q, dict):
                    latency[fam] = {k: q[k] for k in ("p50_s", "p99_s")
                                    if isinstance(q.get(k),
                                                  (int, float))}
        elif name == "memory":
            scope = e.get("scope") or "?"
            peak = e.get("peak_bytes")
            if isinstance(peak, (int, float)):
                # max across streams: the run's true high-water mark
                prev = (memory.get(scope) or {}).get("peak_bytes", 0)
                memory[scope] = {
                    "peak_bytes": max(int(peak), prev),
                    "source": e.get("source")}
    return {
        "spans": dict(spans),
        "counters": {f"{p}:{k}": (n / d if d > 0 else None)
                     for (p, k), (n, d) in counters.items()},
        "end_to_end_s": sum(spans[r]["total_s"] for r in roots),
        "compiles": dict(compiles),
        "device_cells": device_cells,
        "latency": latency,
        "memory": memory,
    }


def diff(base, cand):
    """The structured diff of two collect() aggregates — culprit rows
    ranked by self-time contribution to the end-to-end delta."""
    d_e2e = cand["end_to_end_s"] - base["end_to_end_s"]
    rows = []
    for path in sorted(set(base["spans"]) | set(cand["spans"])):
        a = base["spans"].get(path)
        b = cand["spans"].get(path)
        za = a or {"calls": 0, "total_s": 0.0, "self_s": 0.0}
        zb = b or {"calls": 0, "total_s": 0.0, "self_s": 0.0}
        d_self = zb["self_s"] - za["self_s"]
        rows.append({
            "path": path,
            "only_in": ("candidate" if a is None else
                        "baseline" if b is None else None),
            "calls": (za["calls"], zb["calls"]),
            "total_s": (za["total_s"], zb["total_s"]),
            "self_s": (za["self_s"], zb["self_s"]),
            "d_total_s": zb["total_s"] - za["total_s"],
            "d_self_s": d_self,
            "d_call_s": ((zb["total_s"] / zb["calls"]
                          if zb["calls"] else 0.0)
                         - (za["total_s"] / za["calls"]
                            if za["calls"] else 0.0)),
            "share_of_delta": (d_self / d_e2e
                               if abs(d_e2e) > 1e-12 else None),
        })
    # the culprit ranking: most-regressed self time first (a speedup
    # ranks last, not nowhere — an improved span is still attribution)
    rows.sort(key=lambda r: -r["d_self_s"])
    rates = []
    for key in sorted(set(base["counters"]) | set(cand["counters"])):
        ra, rb = base["counters"].get(key), cand["counters"].get(key)
        rates.append({"counter": key, "baseline": ra, "candidate": rb,
                      "pct": ((rb - ra) / ra * 100.0
                              if isinstance(ra, (int, float)) and ra
                              and isinstance(rb, (int, float))
                              else None)})
    comp = []
    for fn in sorted(set(base["compiles"]) | set(cand["compiles"])):
        ca = base["compiles"].get(fn) or {"count": 0, "compile_s": 0.0}
        cb = cand["compiles"].get(fn) or {"count": 0, "compile_s": 0.0}
        if ca["count"] or cb["count"]:
            comp.append({"fn": fn,
                         "d_count": cb["count"] - ca["count"],
                         "d_compile_s": (cb["compile_s"]
                                         - ca["compile_s"])})
    comp.sort(key=lambda r: -abs(r["d_compile_s"]))
    cells = []
    for cell in sorted(set(base["device_cells"])
                       | set(cand["device_cells"])):
        va = base["device_cells"].get(cell)
        vb = cand["device_cells"].get(cell)
        if va != vb:
            cells.append({"cell": cell, "baseline": va,
                          "candidate": vb})
    lat = []
    for fam in sorted(set(base["latency"]) | set(cand["latency"])):
        qa = base["latency"].get(fam) or {}
        qb = cand["latency"].get(fam) or {}
        for q in ("p50_s", "p99_s"):
            if q in qa or q in qb:
                lat.append({"family": fam, "quantile": q,
                            "baseline": qa.get(q),
                            "candidate": qb.get(q)})
    mem = []
    for scope in sorted(set(base["memory"]) | set(cand["memory"])):
        ma = base["memory"].get(scope) or {}
        mb = cand["memory"].get(scope) or {}
        mem.append({"scope": scope,
                    "baseline_peak_bytes": ma.get("peak_bytes"),
                    "candidate_peak_bytes": mb.get("peak_bytes"),
                    "source": mb.get("source") or ma.get("source")})
    return {
        "end_to_end_s": {"baseline": base["end_to_end_s"],
                         "candidate": cand["end_to_end_s"],
                         "delta": d_e2e},
        "culprits": rows,
        "rates": rates,
        "compiles": comp,
        "device_cells": cells,
        "latency": lat,
        "memory": mem,
        "overlap": sum(1 for r in rows if r["only_in"] is None),
    }


def _f(v, fmt="{:.3f}"):
    return fmt.format(v) if isinstance(v, (int, float)) else "-"


def render(result, base_label, cand_label, top=None, out=sys.stdout):
    e2e = result["end_to_end_s"]
    print(f"baseline : {base_label}", file=out)
    print(f"candidate: {cand_label}", file=out)
    print(f"end-to-end span time: {e2e['baseline']:.3f} s -> "
          f"{e2e['candidate']:.3f} s (delta {e2e['delta']:+.3f} s)",
          file=out)
    rows = result["culprits"]
    if top:
        rows = rows[:top]
    print(f"\n{'culprit span path':<36} {'calls':>11} {'self_s A':>9} "
          f"{'self_s B':>9} {'d_self':>8} {'d_call':>8} {'share':>7}",
          file=out)
    for r in rows:
        ca, cb = r["calls"]
        share = (f"{100 * r['share_of_delta']:>6.1f}%"
                 if r["share_of_delta"] is not None else "      -")
        mark = {"candidate": " +", "baseline": " -"}.get(
            r["only_in"], "")
        print(f"{r['path'] + mark:<36} {f'{ca}->{cb}':>11} "
              f"{r['self_s'][0]:>9.3f} {r['self_s'][1]:>9.3f} "
              f"{r['d_self_s']:>+8.3f} {r['d_call_s']:>+8.3f} "
              f"{share}", file=out)
    if result["rates"]:
        print(f"\n{'counter rate':<44} {'baseline':>13} "
              f"{'candidate':>13} {'pct':>8}", file=out)
        for r in result["rates"]:
            pct = (f"{r['pct']:+.1f}%"
                   if r["pct"] is not None else "-")
            print(f"{r['counter']:<44} "
                  f"{_f(r['baseline'], '{:,.0f}'):>13} "
                  f"{_f(r['candidate'], '{:,.0f}'):>13} {pct:>8}",
                  file=out)
    if result["compiles"]:
        print(f"\n{'compiled fn':<44} {'d_count':>8} "
              f"{'d_compile_s':>12}", file=out)
        for r in result["compiles"]:
            print(f"{r['fn']:<44} {r['d_count']:>+8} "
                  f"{r['d_compile_s']:>+12.3f}", file=out)
    if result["device_cells"]:
        print(f"\n{'device metric cell':<44} {'baseline':>13} "
              f"{'candidate':>13}", file=out)
        for r in result["device_cells"]:
            print(f"{r['cell']:<44} {_f(r['baseline'], '{:.4g}'):>13} "
                  f"{_f(r['candidate'], '{:.4g}'):>13}", file=out)
    if result["latency"]:
        print(f"\n{'latency family':<36} {'q':<6} {'baseline':>10} "
              f"{'candidate':>10}", file=out)
        for r in result["latency"]:
            print(f"{r['family']:<36} {r['quantile']:<6} "
                  f"{_f(r['baseline'], '{:.4f}'):>10} "
                  f"{_f(r['candidate'], '{:.4f}'):>10}", file=out)
    if result["memory"]:
        print(f"\n{'memory scope':<16} {'source':<7} "
              f"{'baseline peak MiB':>18} {'candidate peak MiB':>19}",
              file=out)
        for r in result["memory"]:
            pa = r["baseline_peak_bytes"]
            pb = r["candidate_peak_bytes"]
            print(f"{r['scope']:<16} {str(r['source']):<7} "
                  f"{_f(pa / (1 << 20) if pa else None, '{:,.1f}'):>18} "
                  f"{_f(pb / (1 << 20) if pb else None, '{:,.1f}'):>19}",
                  file=out)


def run_diff(base_spec, cand_spec, archive_root=None):
    """resolve + collect + diff; returns (labels, result)."""
    base_label, base_paths = resolve_side(base_spec, archive_root)
    cand_label, cand_paths = resolve_side(cand_spec, archive_root)
    result = diff(collect(read_events(base_paths)),
                  collect(read_events(cand_paths)))
    return base_label, cand_label, result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_diff", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("baseline",
                    help="telemetry JSONL path(s, comma-separated) "
                         "or an archived run id")
    ap.add_argument("candidate",
                    help="the run under suspicion, same forms")
    ap.add_argument("--archive", metavar="DIR",
                    help="archive root for run-id resolution "
                         "(default: $CPR_OBS_ARCHIVE or runs/archive)")
    ap.add_argument("--top", type=int, metavar="N",
                    help="print at most N culprit rows")
    ap.add_argument("--json", action="store_true",
                    help="dump the structured diff as JSON")
    args = ap.parse_args(argv)
    try:
        base_label, cand_label, result = run_diff(
            args.baseline, args.candidate, args.archive)
    except OSError as e:
        print(f"trace_diff: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"baseline": base_label,
                          "candidate": cand_label, **result},
                         indent=2, sort_keys=True))
    else:
        render(result, base_label, cand_label, top=args.top)
    return 0 if result["overlap"] else 1


if __name__ == "__main__":
    sys.exit(main())
