"""Frontier-batched MDP compile smoke (`make compile-smoke`).

Proves the frontier compile pipeline (docs/MDP.md) end-to-end on the
CPU CI host:

  1  an A/B child compiles the generic bitcoin model (dag_size_cutoff
     controls the state count) three ways — serial `Compiler`,
     frontier inline (workers=1), and frontier with FORCED multi-worker
     expansion — asserts all three MDPs byte-identical (sha256 over
     the transition columns + start map), and reports states/sec for
     each;
  2  throughput floor, core-adaptive: on a multi-core host the best
     frontier rate must beat the serial BFS >= 2x (>= 4x is the target
     at >= 4 cores); the 1-core CI cannot express a multi-core
     speedup, so there the floor is parity (1.0x) for the inline
     frontier — override with CPR_COMPILE_SMOKE_FLOOR;
  3  a kill+resume leg: CPR_FAULT_INJECT=kill@compile_round=3 crashes
     a checkpointed compile mid-BFS through the real fault grammar,
     a fresh process resumes from the npz checkpoint, and the resumed
     MDP's hash must equal the uninterrupted one byte-for-byte;
  4  every trace passes `trace_summary --validate --expect
     mdp_compile`, and the A/B trace ingests into a perf ledger:
     `mdp_compile_states_per_sec` rows must land at BOTH cfg_workers=1
     and cfg_workers=N and every banked row must clear the regression
     gate.

Usage: python tools/compile_smoke.py [workdir]   (default /tmp/...)
Env: CPR_COMPILE_SMOKE_CUTOFF (default 6), CPR_COMPILE_SMOKE_WORKERS
(default min(4, cores) but at least 2), CPR_COMPILE_SMOKE_FLOOR.
"""

import json
import os
import subprocess
import sys
import textwrap

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from cpr_tpu.perf.gate import gate_row, gate_summary  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402

CUTOFF = int(os.environ.get("CPR_COMPILE_SMOKE_CUTOFF", "6"))
CORES = os.cpu_count() or 1
WORKERS = int(os.environ.get("CPR_COMPILE_SMOKE_WORKERS",
                             str(max(2, min(4, CORES)))))
# acceptance floor: >= 2x over serial with multi-worker expansion on a
# multi-core host (>= 4x target at >= 4 cores).  On the 1-core CI the
# frontier cannot beat the serial BFS: ~95% of compile wall-clock is
# model.apply itself (cProfile, generic bitcoin@6), which the frontier
# parallelizes across cores — the batched bookkeeping only wins the
# remaining ~5%.  There the floor is parity within measurement noise.
FLOOR = float(os.environ.get(
    "CPR_COMPILE_SMOKE_FLOOR", "2.0" if CORES >= 2 else "0.85"))
WALL_S = 900.0


def _log(msg):
    print(f"compile-smoke: {msg}", file=sys.stderr)


def _child_env(trace, extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", CPR_TELEMETRY=trace)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def _validate_stream(trace, expect):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, trace, "--validate", "--expect", expect],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


_COMMON = textwrap.dedent("""\
    import hashlib, json, os

    from cpr_tpu import telemetry
    from cpr_tpu.telemetry import now

    def model():
        from cpr_tpu.mdp.generic import SingleAgent, get_protocol

        return SingleAgent(
            get_protocol("bitcoin"), alpha=0.3, gamma=0.5,
            collect_garbage="simple", merge_isomorphic=True,
            truncate_common_chain=True,
            dag_size_cutoff=int(os.environ["CPR_SMOKE_CUTOFF"]))

    def mdp_hash(m):
        h = hashlib.sha256()
        for col in m.arrays():
            h.update(col.tobytes())
        h.update(repr(sorted(m.start.items())).encode())
        h.update(f"{m.n_states},{m.n_actions}".encode())
        return h.hexdigest()
""")

# serial vs frontier(1) vs frontier(N): byte-identity + states/sec
_AB_CHILD = _COMMON + textwrap.dedent("""\

    from cpr_tpu.mdp.compiler import Compiler
    from cpr_tpu.mdp.frontier import FrontierCompiler

    cutoff = int(os.environ["CPR_SMOKE_CUTOFF"])
    workers = int(os.environ["CPR_SMOKE_WORKERS"])
    telemetry.current().manifest(config={"role": "compile-smoke"})

    t0 = now()
    ref = Compiler(model()).mdp()
    serial_s = now() - t0
    serial_rate = ref.n_states / serial_s
    ref_hash = mdp_hash(ref)

    rates = {}
    for w in (1, workers):
        fc = FrontierCompiler(model(), n_workers=w,
                              protocol="bitcoin", cutoff=cutoff)
        t0 = now()
        m = fc.mdp()
        dt = now() - t0
        if mdp_hash(m) != ref_hash:
            raise SystemExit(f"frontier (workers={w}) NOT "
                             f"byte-identical to the serial compiler")
        rates[str(w)] = m.n_states / dt

    with open(os.environ["CPR_SMOKE_OUT"], "w") as f:
        json.dump(dict(states=ref.n_states,
                       transitions=ref.n_transitions,
                       hash=ref_hash, serial_rate=serial_rate,
                       rates=rates), f)
""")

# checkpointed compile killed mid-BFS through the real fault grammar
_KILL_CHILD = _COMMON + textwrap.dedent("""\

    from cpr_tpu.mdp.frontier import FrontierCompiler

    telemetry.current().manifest(config={"role": "compile-smoke-kill"})
    FrontierCompiler(model(),
                     checkpoint_path=os.environ["CPR_SMOKE_CK"]).mdp()
    raise SystemExit("compile survived kill@compile_round=3")
""")

_RESUME_CHILD = _COMMON + textwrap.dedent("""\

    from cpr_tpu.mdp.frontier import FrontierCompiler

    telemetry.current().manifest(
        config={"role": "compile-smoke-resume"})
    ck = os.environ["CPR_SMOKE_CK"]
    assert os.path.exists(ck), "no checkpoint left by the killed run"
    m = FrontierCompiler(model(), checkpoint_path=ck).mdp()
    assert not os.path.exists(ck), "checkpoint not cleaned up"
    with open(os.environ["CPR_SMOKE_OUT"], "w") as f:
        json.dump(dict(hash=mdp_hash(m)), f)
""")


def _run_child(code, env, what):
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=ROOT,
                       capture_output=True, text=True, timeout=WALL_S)
    sys.stderr.write(r.stdout)
    if r.returncode != 0:
        sys.stderr.write(r.stderr)
        raise SystemExit(f"{what} child failed rc={r.returncode}")
    return r


def _ab_leg(work):
    trace = os.path.join(work, "compile_ab.jsonl")
    out = os.path.join(work, "compile_ab.json")
    env = _child_env(trace, {
        "CPR_SMOKE_CUTOFF": str(CUTOFF),
        "CPR_SMOKE_WORKERS": str(WORKERS),
        "CPR_SMOKE_OUT": out,
    })
    _run_child(_AB_CHILD, env, "A/B")
    _validate_stream(trace, "mdp_compile")
    with open(out) as f:
        payload = json.load(f)
    best = max(payload["rates"].values())
    speedup = best / payload["serial_rate"]
    _log(f"A/B: {payload['states']} states, "
         f"serial {payload['serial_rate']:.0f} st/s, frontier "
         + ", ".join(f"w={w} {r:.0f} st/s"
                     for w, r in sorted(payload["rates"].items()))
         + f" -> best {speedup:.2f}x (floor {FLOOR:.2f}x on "
         f"{CORES} cores)")
    if speedup < FLOOR:
        raise SystemExit(f"frontier compile speedup {speedup:.2f}x "
                         f"under the {FLOOR:.2f}x floor")
    return payload, trace, speedup


def _kill_resume_leg(work, ref_hash):
    trace = os.path.join(work, "compile_resume.jsonl")
    ck = os.path.join(work, "compile_ck.npz")
    out = os.path.join(work, "compile_resume.json")
    for p in (trace, ck, ck + ".json", out):
        if os.path.exists(p):
            os.remove(p)
    env = _child_env(trace, {
        "CPR_SMOKE_CUTOFF": str(CUTOFF),
        "CPR_SMOKE_CK": ck,
        "CPR_FAULT_INJECT": "kill@compile_round=3",
    })
    r = subprocess.run([sys.executable, "-c", _KILL_CHILD], env=env,
                       cwd=ROOT, capture_output=True, text=True,
                       timeout=WALL_S)
    if r.returncode == 0:
        raise SystemExit("kill@compile_round=3 did not fire")
    if not os.path.exists(ck):
        sys.stderr.write(r.stderr)
        raise SystemExit("killed compile left no checkpoint")
    _log("kill@compile_round=3 fired, checkpoint on disk")

    env = _child_env(trace, {
        "CPR_SMOKE_CUTOFF": str(CUTOFF),
        "CPR_SMOKE_CK": ck,
        "CPR_SMOKE_OUT": out,
    })
    env.pop("CPR_FAULT_INJECT", None)
    _run_child(_RESUME_CHILD, env, "resume")
    _validate_stream(trace, "mdp_compile")
    with open(out) as f:
        resumed = json.load(f)
    if resumed["hash"] != ref_hash:
        raise SystemExit("resumed compile NOT byte-identical to the "
                         "uninterrupted one")
    _log("resumed compile byte-identical to the uninterrupted run")
    return trace


def _bank_and_gate(work, trace):
    """The A/B trace into a ledger; mdp_compile_states_per_sec rows
    must land at both worker counts and every row must clear the
    regression gate.  (The resume trace is validated but not banked:
    a resumed run's states/sec counts only post-resume wall-clock, so
    its rate would not be comparable.)"""
    ledger = Ledger(os.path.join(work, "perf_ledger.jsonl"))
    n = ledger.ingest_trace(trace)
    records = ledger.records()
    rows = [r for r in records
            if r.get("metric") == "mdp_compile_states_per_sec"]
    got = {r.get("config", {}).get("cfg_workers") for r in rows}
    if not {1, WORKERS} <= got:
        raise SystemExit(f"mdp_compile_states_per_sec banked at worker "
                         f"counts {sorted(got)}, need both 1 and "
                         f"{WORKERS}")
    results = [gate_row(r, records) for r in records]
    summary = gate_summary(results)
    if not summary["ok"]:
        bad = [res for res in results if res["verdict"] == "fail"]
        raise SystemExit(f"compile perf gate failed: {bad}")
    return n, summary


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-compile-smoke"
    os.makedirs(work, exist_ok=True)

    payload, trace_ab, speedup = _ab_leg(work)
    _kill_resume_leg(work, payload["hash"])
    n, summary = _bank_and_gate(work, trace_ab)
    print(f"compile-smoke: PASS (serial vs frontier vs "
          f"{WORKERS}-worker byte-identical on bitcoin@{CUTOFF} "
          f"[{payload['states']} states]; best {speedup:.2f}x >= "
          f"{FLOOR:.2f}x floor on {CORES} cores; kill@compile_round=3 "
          f"+ resume byte-identical; banked {n} ledger rows incl. "
          f"mdp_compile_states_per_sec at workers 1 and {WORKERS}; "
          f"gate {summary})")


if __name__ == "__main__":
    main()
