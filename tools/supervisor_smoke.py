"""Supervised-subprocess smoke (`make supervisor-smoke`).

Proves the cpr_tpu/supervisor contract end-to-end with deterministic
fault injection (no wedgeable device required), on three scenarios:

  1  hang@probe  — the probe-before-run child wedges: supervise must
     raise ProbeFailure in ~probe_timeout seconds without ever
     committing the workload;
  2  hang@run    — the workload child wedges at its `run` fault point:
     the heartbeat watchdog must declare a stall in ~quiet_s (well
     under the wall budget), a fresh probe must gate exactly one warm
     restart, the restarted child re-fires the per-process one-shot
     and stalls again, and supervise escalates;
  3  the terminal rung — the same workload with injection off must run
     clean (what bench.py's CPU fallback does after an escalation).

Asserts the ISSUE-8 acceptance criterion: both injected scenarios
resolve in < 60 s (stall detection is heartbeat-driven, not
wall-budget-driven), the typed `supervisor` event trail shows exactly
2 heartbeat_stalls / 1 warm_restart / 1 escalation for scenario 2, and
the emitted trace passes
`tools/trace_summary.py --validate --expect supervisor`.

Usage: python tools/supervisor_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from cpr_tpu import supervisor, telemetry  # noqa: E402
from cpr_tpu.resilience import FAULT_ENV_VAR  # noqa: E402

# tight-but-safe smoke knobs: quiet_s only needs to beat a few beat
# periods; probes run the real --probe child (jax import, CPU backend)
QUIET_S = 3.0
HEARTBEAT_S = 0.5
WALL_S = 45.0
PROBE_TIMEOUT_S = 30.0


def _cfg(**kw):
    base = dict(wall_timeout_s=WALL_S, quiet_s=QUIET_S,
                heartbeat_s=HEARTBEAT_S, probe_timeout_s=PROBE_TIMEOUT_S,
                retry_pause_s=0.2)
    base.update(kw)
    return supervisor.SupervisorConfig(**base)


def _env(fault=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(FAULT_ENV_VAR, None)
    if fault:
        env[FAULT_ENV_VAR] = fault
    return env


def _events(path, action=None):
    out = []
    with open(path) as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") == "event" and e.get("name") == "supervisor" \
                    and (action is None or e.get("action") == action):
                out.append(e)
    return out


def _validate_stream(path, expect):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, path, "--validate", "--expect", expect],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {path}")


def main():
    work = (sys.argv[1] if len(sys.argv) > 1
            else "/tmp/cpr-supervisor-smoke")
    os.makedirs(work, exist_ok=True)
    tele_path = os.path.join(work, "supervisor.jsonl")
    if os.path.exists(tele_path):
        os.remove(tele_path)
    os.environ[telemetry.TELEMETRY_ENV_VAR] = tele_path
    telemetry.configure(tele_path)

    print("supervisor-smoke: scenario 1 (hang@probe -> ProbeFailure, "
          "bounded by probe_timeout)", file=sys.stderr)
    t0 = time.time()
    try:
        supervisor.supervise(
            supervisor.selftest_cmd(), site="smoke:probe-wedge",
            config=_cfg(probe_timeout_s=10.0), env=_env("hang@probe"))
        raise SystemExit("scenario 1: supervise succeeded despite a "
                         "wedged probe")
    except supervisor.ProbeFailure:
        dt1 = time.time() - t0
    if dt1 >= 60.0:
        raise SystemExit(f"scenario 1 took {dt1:.0f}s (want < 60)")
    print(f"supervisor-smoke: probe wedge detected in {dt1:.1f}s",
          file=sys.stderr)

    print("supervisor-smoke: scenario 2 (hang@run -> stall, one warm "
          "restart, escalation)", file=sys.stderr)
    t0 = time.time()
    try:
        supervisor.supervise(
            supervisor.selftest_cmd(), site="smoke:run-wedge",
            config=_cfg(), env=_env("hang@run"))
        raise SystemExit("scenario 2: supervise succeeded despite a "
                         "wedged workload")
    except supervisor.SupervisedHang:
        dt2 = time.time() - t0
    if dt2 >= 60.0:
        raise SystemExit(f"scenario 2 took {dt2:.0f}s (want < 60: "
                         f"stall detection must not burn wall budget)")
    print(f"supervisor-smoke: stall+restart+escalation in {dt2:.1f}s",
          file=sys.stderr)

    print("supervisor-smoke: scenario 3 (terminal rung: injection off, "
          "clean run)", file=sys.stderr)
    a = supervisor.run_child(supervisor.selftest_cmd(),
                             wall_timeout_s=WALL_S, quiet_s=QUIET_S,
                             heartbeat_s=HEARTBEAT_S, env=_env())
    if a.status != "ok" or not a.json_lines:
        raise SystemExit(f"scenario 3: clean child failed "
                         f"(status={a.status} rc={a.rc})")

    # the validated stream needs a backend-bearing manifest; emitted
    # LAST so the parent stays backend-free while children run (CPU
    # forced via jax.config — the axon plugin ignores JAX_PLATFORMS)
    import jax

    jax.config.update("jax_platforms", "cpu")
    telemetry.current().manifest(config=dict(smoke="supervisor"))
    telemetry.configure(None)

    stalls = _events(tele_path, "heartbeat_stall")
    restarts = _events(tele_path, "warm_restart")
    escalations = _events(tele_path, "escalation")
    probes = _events(tele_path, "probe")
    run_stalls = [e for e in stalls if e.get("site") == "smoke:run-wedge"]
    if len(run_stalls) != 2:
        raise SystemExit(f"want exactly 2 heartbeat_stalls for the "
                         f"run wedge, got {len(run_stalls)}")
    if [e.get("site") for e in restarts] != ["smoke:run-wedge"]:
        raise SystemExit(f"want exactly 1 warm_restart (run wedge), "
                         f"got {len(restarts)}")
    if len([e for e in escalations
            if e.get("site") == "smoke:run-wedge"]) != 1:
        raise SystemExit("want exactly 1 escalation for the run wedge")
    if len([e for e in escalations
            if e.get("site") == "smoke:probe-wedge"]) != 1:
        raise SystemExit("want exactly 1 escalation for the probe wedge")
    if len(probes) < 3:  # scenario 1 probe + scenario 2 pre-run + gate
        raise SystemExit(f"want >= 3 probe events, got {len(probes)}")
    _validate_stream(tele_path, "supervisor,fault_injected")
    print(f"supervisor-smoke: PASS (probe wedge {dt1:.1f}s, run wedge "
          f"{dt2:.1f}s incl. 1 warm restart; trail: {len(probes)} "
          f"probes, 2 stalls, 1 restart, 2 escalations)")


if __name__ == "__main__":
    main()
