"""Perf-ledger trend report + runtime regression gate (cpr_tpu/perf).

Reads the banked bench trail — either a persisted ledger JSONL or the
tracked `BENCH*.json` banks directly — and renders per-metric trend
tables plus a gate verdict per metric x backend: the newest banked row
is judged against the best earlier same-backend rows (median/MAD band,
outage/error rows never baselines; see docs/OBSERVABILITY.md).  When
the trail holds the same metric at multiple device counts (ledger-v4
cfg_devices fingerprints), a device-scaling table — value, speedup,
parallel efficiency per device count — is printed and written to the
markdown report (docs/SCALING.md "blessing a scaling row").

    python tools/perf_report.py                      # tracked banks
    python tools/perf_report.py runs/perf_ledger.jsonl
    python tools/perf_report.py --gate               # nonzero on fail
    python tools/perf_report.py --since 3 --metric nakamoto
    python tools/perf_report.py --markdown runs/perf_report.md
    python tools/perf_report.py --trace /tmp/run.jsonl   # + span rates
    python tools/perf_report.py --gate --attribute   # + culprit spans
    make perf-gate                                   # CI entry point

`--attribute` chases every FAIL/WARN verdict through the run archive
(the v15 `perf_gate` verdict carries the candidate's and baseline
rows' run ids; cpr_tpu.perf.archive maps a run id back to its
telemetry streams) and prints a tools/trace_diff.py culprit table —
the span paths whose self-time moved, ranked by contribution to the
end-to-end delta — so a red gate names WHERE the regression lives,
not just that one exists.

Exit codes: 0 = no failed gate (warn/skip/pass), 1 = at least one
`fail` verdict in --gate/--attribute mode, 2 = usage error.  To bless an
intentional perf change (a config move, an accepted slowdown), bank
the new row — once it is the newest banked round it IS the candidate,
and future gates judge against the best history including it; the
verdict band is against best-banked, so a blessed slower row only
stops gating once the old fast rows age past --since or the config
fingerprint moves.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from cpr_tpu import perf  # noqa: E402
from cpr_tpu.resilience import atomic_write_text  # noqa: E402


def _round_rank(rec):
    """Sort key placing unknown-round rows (the suffix-less current
    bank, live bench rows) AFTER every numbered round — they are the
    most recent state of the trail."""
    rnd = rec.get("round")
    return (1, 0) if rnd is None else (0, rnd)


def load_records(args) -> list[dict]:
    records = []
    if args.ledger:
        records.extend(perf.Ledger(args.ledger).records())
    else:
        records.extend(
            perf.normalize_row(row, source=src, rnd=rnd, tail_hint=hint)
            for row, src, rnd, hint in perf.iter_bank_rows(args.root))
    for trace in args.trace or ():
        records.extend(perf.normalize_row(row, source=src)
                       for row, src in perf.iter_trace_rows(trace))
    if args.since is not None:
        records = [r for r in records
                   if r.get("round") is None or r["round"] >= args.since]
    if args.metric:
        records = [r for r in records
                   if str(r.get("metric", "")).startswith(args.metric)]
    return records


def gate_all(records) -> list[dict]:
    """One gate per metric x backend: newest row (by round, unknown
    rounds newest) is the candidate, everything earlier the history."""
    groups = {}
    for r in records:
        groups.setdefault((r.get("metric"), r.get("backend")), []).append(r)
    results = []
    for key in sorted(groups, key=lambda k: (str(k[0]), str(k[1]))):
        rows = sorted(groups[key],
                      key=lambda r: (_round_rank(r), str(r.get("source"))))
        candidate = rows[-1]
        history = [r for r in records if r is not candidate]
        results.append(perf.gate_row(candidate, history))
    return results


def _fmt_val(v):
    if v is None:
        return "-"
    return f"{v:,.0f}" if abs(v) >= 1000 else f"{v:.4g}"


def _flags(rec):
    out = []
    if rec.get("outage"):
        out.append("outage")
    if rec.get("error"):
        out.append("error")
    return ",".join(out)


def trend_lines(records):
    yield (f"{'metric':<44} {'backend':<7} {'round':>5} {'value':>14} "
           f"{'check':>8} {'source':<26} flags")
    key = lambda r: (str(r.get("metric")), str(r.get("backend")),  # noqa: E731
                     _round_rank(r), str(r.get("source")))
    for r in sorted(records, key=key):
        rnd = "-" if r.get("round") is None else r["round"]
        check = "-" if r.get("check") is None else f"{r['check']:.4g}"
        yield (f"{r.get('metric', '?'):<44} {str(r.get('backend')):<7} "
               f"{rnd:>5} {_fmt_val(r.get('value')):>14} {check:>8} "
               f"{str(r.get('source')):<26} {_flags(r)}")


def scaling_groups(records) -> list[dict]:
    """Device-count scaling view (ledger v4): group clean measurement
    rows by (metric, backend, config minus cfg_devices) and keep the
    groups spanning >= 2 device counts.  Per device count the BEST
    banked value (direction-aware) represents it; speedup is vs the
    group's smallest device count and efficiency = speedup / device
    ratio — the (near-)linear-scaling evidence docs/SCALING.md asks
    for, instead of 'ran on 8'."""
    groups = {}
    for r in records:
        if r.get("outage") or r.get("error") or r.get("probe"):
            continue
        if not isinstance(r.get("value"), (int, float)) or r["value"] <= 0:
            continue
        cfg = dict(r.get("config") or {})
        try:
            dev = int(cfg.pop("cfg_devices", 1))
        except (TypeError, ValueError):
            continue
        key = (str(r.get("metric")), str(r.get("backend")),
               tuple(sorted((k, str(v)) for k, v in cfg.items())))
        groups.setdefault(key, {}).setdefault(dev, []).append(r)
    out = []
    for (metric, backend, _cfg) in sorted(groups):
        by_dev = groups[(metric, backend, _cfg)]
        if len(by_dev) < 2:
            continue
        lower = any(r.get("direction") == "lower"
                    for rows in by_dev.values() for r in rows)
        pick = min if lower else max
        best = {dev: pick(r["value"] for r in rows)
                for dev, rows in by_dev.items()}
        base_dev = min(best)
        rows = []
        for dev in sorted(best):
            speed = (best[base_dev] / best[dev] if lower
                     else best[dev] / best[base_dev])
            rows.append(dict(devices=dev, value=best[dev],
                             speedup=speed,
                             efficiency=speed / (dev / base_dev)))
        out.append(dict(metric=metric, backend=backend,
                        base_devices=base_dev, rows=rows))
    return out


def scaling_lines(scaling):
    yield (f"{'metric':<44} {'backend':<7} {'devices':>7} "
           f"{'value':>14} {'speedup':>8} {'eff':>6}")
    for grp in scaling:
        for row in grp["rows"]:
            yield (f"{grp['metric']:<44} {grp['backend']:<7} "
                   f"{row['devices']:>7} {_fmt_val(row['value']):>14} "
                   f"{row['speedup']:>7.2f}x {row['efficiency']:>5.0%}")


def gate_lines(results):
    for res in results:
        base = res.get("baseline")
        against = ("" if base is None else
                   f" median={_fmt_val(base['median'])} "
                   f"best={_fmt_val(base['best'])}"
                   f"@{base.get('best_source')} n={base['n']}")
        drift = " [config-drift]" if res.get("config_drift") else ""
        lower = " [lower-is-better]" if res.get("direction") == "lower" \
            else ""
        yield (f"gate: {res['metric']} [{res['backend']}] "
               f"{res['verdict'].upper()}{drift}{lower} "
               f"value={_fmt_val(res['value'])}{against}")
        if res["verdict"] != "pass":
            yield f"      {res['reason']}"


def attribute_failures(results, archive_root=None, out=sys.stdout) -> int:
    """Chase each FAIL/WARN verdict into a trace_diff culprit table
    via the run archive.  Returns how many verdicts were attributed;
    verdicts without an archived candidate/baseline run pair say so
    and are skipped (pre-v15 ledgers carry no run ids)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import trace_diff  # noqa: E402 — sibling tool, path set above
    attributed = 0
    for res in results:
        if res["verdict"] not in ("fail", "warn"):
            continue
        cand = res.get("run")
        bases = [b for b in (res.get("baseline_runs") or ())
                 if b and b != cand]
        if not cand or not bases:
            print(f"attribute: {res['metric']} [{res['backend']}] "
                  f"{res['verdict'].upper()}: no archived run pair "
                  f"(candidate run={cand}, baseline runs={bases or '-'})",
                  file=out)
            continue
        try:
            bl, cl, d = trace_diff.run_diff(bases[0], cand,
                                            archive_root)
        except SystemExit as e:
            print(f"attribute: {res['metric']}: {e}", file=out)
            continue
        print(f"\nattribution: {res['metric']} [{res['backend']}] "
              f"{res['verdict'].upper()}", file=out)
        trace_diff.render(d, f"run {bl}", f"run {cl}", top=10, out=out)
        attributed += 1
    return attributed


def markdown_report(records, results, summary, scaling=()) -> str:
    lines = ["# Perf ledger report", "",
             f"{len(records)} ledger rows; gate: "
             f"{summary['fail']} fail / {summary['warn']} warn / "
             f"{summary['pass']} pass / {summary['skip']} skip", "",
             "## Gate verdicts", "",
             "| metric | backend | verdict | value | baseline median | "
             "best (source) |", "|---|---|---|---|---|---|"]
    for res in results:
        base = res.get("baseline")
        med = "-" if base is None else _fmt_val(base["median"])
        best = ("-" if base is None else
                f"{_fmt_val(base['best'])} ({base.get('best_source')})")
        drift = " (config drift)" if res.get("config_drift") else ""
        lines.append(f"| {res['metric']} | {res['backend']} | "
                     f"{res['verdict']}{drift} | {_fmt_val(res['value'])} "
                     f"| {med} | {best} |")
    if scaling:
        lines += ["", "## Device scaling", "",
                  "| metric | backend | devices | value | speedup | "
                  "efficiency |", "|---|---|---|---|---|---|"]
        for grp in scaling:
            for row in grp["rows"]:
                lines.append(
                    f"| {grp['metric']} | {grp['backend']} | "
                    f"{row['devices']} | {_fmt_val(row['value'])} | "
                    f"{row['speedup']:.2f}x | "
                    f"{row['efficiency']:.0%} |")
    lines += ["", "## Banked trail", "",
              "| metric | backend | round | value | check | source | "
              "flags |", "|---|---|---|---|---|---|---|"]
    key = lambda r: (str(r.get("metric")), str(r.get("backend")),  # noqa: E731
                     _round_rank(r), str(r.get("source")))
    for r in sorted(records, key=key):
        rnd = "-" if r.get("round") is None else r["round"]
        check = "-" if r.get("check") is None else f"{r['check']:.4g}"
        lines.append(f"| {r.get('metric', '?')} | {r.get('backend')} | "
                     f"{rnd} | {_fmt_val(r.get('value'))} | {check} | "
                     f"{r.get('source')} | {_flags(r) or '-'} |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="perf_report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("ledger", nargs="?",
                    help="ledger JSONL to read (default: scan the "
                         "tracked BENCH*.json banks under --root)")
    ap.add_argument("--root", default=REPO,
                    help="artifact root holding the BENCH*.json banks")
    ap.add_argument("--trace", action="append", metavar="JSONL",
                    help="also lift span rates from a telemetry trace; "
                         "repeatable")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when any metric's newest row FAILS "
                         "against its banked same-backend baseline")
    ap.add_argument("--attribute", action="store_true",
                    help="chase FAIL/WARN verdicts through the run "
                         "archive into trace_diff culprit tables "
                         "(implies gate exit semantics)")
    ap.add_argument("--archive", metavar="DIR",
                    help="archive root for --attribute (default: "
                         "$CPR_OBS_ARCHIVE or runs/archive)")
    ap.add_argument("--since", type=int, metavar="ROUND",
                    help="only rows banked at round >= ROUND "
                         "(unknown-round rows are kept)")
    ap.add_argument("--metric", metavar="PREFIX",
                    help="only metrics starting with PREFIX")
    ap.add_argument("--markdown", metavar="FILE",
                    help="also write the report as markdown (atomic)")
    args = ap.parse_args(argv)

    try:
        records = load_records(args)
    except OSError as e:
        print(f"perf_report: {e}", file=sys.stderr)
        return 2
    if not records:
        print("perf_report: no ledger rows matched", file=sys.stderr)
        return 2 if not args.gate else 1
    results = gate_all(records)
    summary = perf.gate_summary(results)
    scaling = scaling_groups(records)

    for line in trend_lines(records):
        print(line)
    print()
    if scaling:
        for line in scaling_lines(scaling):
            print(line)
        print()
    for line in gate_lines(results):
        print(line)
    print(f"perf-gate: {'PASS' if summary['ok'] else 'FAIL'} "
          f"({summary['fail']} fail, {summary['warn']} warn, "
          f"{summary['pass']} pass, {summary['skip']} skip)")
    if args.attribute:
        attribute_failures(results, archive_root=args.archive)
    if args.markdown:
        atomic_write_text(args.markdown,
                          markdown_report(records, results, summary,
                                          scaling))
        print(f"perf_report: wrote {args.markdown}", file=sys.stderr)
    return 0 if (summary["ok"]
                 or not (args.gate or args.attribute)) else 1


if __name__ == "__main__":
    sys.exit(main())
