"""Stage-3 ethereum-fault bisect: construct stubs at the minimal crasher.

Stage 2 narrowed the fault to needing BOTH axes large: 4096 envs x
capacity 72 passes, 256 envs x capacity 264 passes, 1024 envs x
capacity 264 crashes.  Stage 3 works at the minimal crashing shape
(1024 x hint 256) and toggles one thing at a time: scan length, policy,
and the ethereum-specific kernels (chain_window, select_uncles).
Control (unmodified crasher) runs LAST.

Usage: python tools/tpu_eth_bisect3.py [max_candidates]
"""

import sys

# run as a script from anywhere: the tools dir is sys.path[0] only for
# direct execution, so resolve it explicitly
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from bisect_common import run_candidates  # noqa: E402
from tpu_eth_bisect2 import scan, STUB_SELECT, STUB_WINDOW  # noqa: E402

CANDIDATES = [
    # axis: scan length (is the 256-step scan needed, or just the shape?)
    ("n1024_h256_scan64", scan(1024, 256, 64)),
    # axis: policy
    ("n1024_h256_honest", scan(1024, 256, 256, policy="honest")),
    # axis: ethereum-specific kernels
    ("n1024_h256_stub_window", scan(1024, 256, 256, stub=STUB_WINDOW)),
    ("n1024_h256_stub_select", scan(1024, 256, 256, stub=STUB_SELECT)),
    ("n1024_h256_stub_both", scan(1024, 256, 256,
                                  stub=STUB_WINDOW + STUB_SELECT)),
    # control: the known crasher, unmodified (LAST)
    ("n1024_h256_control", scan(1024, 256, 256)),
]

if __name__ == "__main__":
    limit = int(sys.argv[1]) if len(sys.argv) > 1 else None
    run_candidates(CANDIDATES, limit)
