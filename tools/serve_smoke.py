"""Serving-layer smoke (`make serve-smoke`).

Proves the cpr_tpu/serve contract end-to-end on CPU, the way
production would run it — a supervised server child, concurrent
clients, a graceful SIGTERM drain — and banks the measured throughput:

  1  launch `python -m cpr_tpu.serve.server` under `supervisor.run_child`
     (heartbeat watchdog live, `on_start` capturing the Popen handle),
     serving a tiny trained-net snapshot written via
     `driver.export_policy_snapshot` alongside the scripted policies;
  2  ~32 concurrent scripted clients across all three endpoint
     families: seeded + unseeded policy episodes (`episode.run`,
     scripted and 'ppo'), interactive episodes stepped action-by-action
     to completion, netsim honest-net queries and break-even lookups;
  3  a full-occupancy policy flood, with sustained device throughput
     taken from the stats delta (steps / busy dispatch seconds) and
     asserted within CPR_SERVE_MIN_FRAC (default 0.8) of an equivalent
     batch `rollout()` measured in-process afterwards — the ISSUE-9
     acceptance band;
  4  SIGTERM: the child must drain (serve `drain`/`report`/`stop`
     events) and exit 0, the drain report must carry sane request
     latencies (0 < p50_s <= p99_s < wall), the trace must pass
     `trace_summary --validate --expect serve,device_metrics,request`,
     and the report's `serve_steps_per_sec` / `serve_p50_s` /
     `serve_p99_s` rows must ingest into the perf ledger and clear the
     (direction-aware) regression gate;
  5  the smoke's own client side writes a second telemetry stream, and
     `trace_stitch` over server + client streams must pair at least
     one request trace on both sides of the wire under the shared run
     id — the end-to-end proof of the v8 trace context.

The <2% tracing-overhead acceptance is enforced by the same
CPR_SERVE_MIN_FRAC throughput floor as ISSUE 9: the flood runs with
CPR_TELEMETRY + request events live, so a tracing regression eats
straight into the measured serve/rollout fraction.

Usage: python tools/serve_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from cpr_tpu import supervisor, telemetry  # noqa: E402
from cpr_tpu.perf.gate import gate_row, gate_summary  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402
from cpr_tpu.serve.protocol import ServeClient  # noqa: E402

# episode length == burst length: a lane admitted at a burst boundary
# completes exactly at the burst's last step, so full-occupancy floods
# waste no post-done device work between retire and backfill
MAX_STEPS = 512
LANES = 16
BURST = 512
N_CLIENTS = 32
FLOOD_EPISODES = 512
BASELINE_STEPS = 512
READY_TIMEOUT_S = 300.0
WALL_S = 600.0


def _log(msg):
    print(f"serve-smoke: {msg}", file=sys.stderr)


def _child_cmd(workdir, snap):
    return [sys.executable, "-m", "cpr_tpu.serve.server",
            "--protocol", "nakamoto", "--max-steps", str(MAX_STEPS),
            "--lanes", str(LANES), "--burst", str(BURST),
            "--policy-snapshot", snap, "--heartbeat-s", "0.5",
            "--ready-file", os.path.join(workdir, "ready.json")]


def _child_env(workdir, trace):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CPR_TELEMETRY=trace, CPR_DEVICE_METRICS="1",
               CPR_TPU_CACHE=os.path.join(workdir, "cache"))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _write_snapshot(path, env):
    """A tiny randomly-initialized ActorCritic: the snapshot format and
    the serving path are what's under test, not the policy quality."""
    import jax
    import jax.numpy as jnp

    from cpr_tpu.train.driver import export_policy_snapshot
    from cpr_tpu.train.ppo import ActorCritic

    hidden = (16,)
    net = ActorCritic(env.n_actions, hidden)
    net_params = net.init(jax.random.PRNGKey(0),
                          jnp.zeros(env.observation_length))
    export_policy_snapshot(path, net_params, protocol="nakamoto",
                           n_actions=env.n_actions,
                           observation_length=env.observation_length,
                           hidden=hidden)


def _wait_ready(path, proc):
    deadline = time.time() + READY_TIMEOUT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            raise SystemExit(f"server child exited rc={proc.returncode} "
                             f"before becoming ready")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            time.sleep(0.25)
    raise SystemExit(f"server not ready within {READY_TIMEOUT_S:.0f}s")


def _policy_client(port, policy, seed):
    with ServeClient("127.0.0.1", port) as c:
        req = dict(policy=policy) if seed is None else \
            dict(policy=policy, seed=seed)
        r = c.request("episode.run", **req)
        assert r.get("ok"), f"episode.run({policy}, {seed}): {r}"
        ep = r["episode"]
        assert ep["n_steps"] >= 1 and "relative_reward" in ep, r
        return r


def _interactive_client(port, seed):
    with ServeClient("127.0.0.1", port) as c:
        r = c.request("episode.open", seed=seed)
        assert r.get("ok"), f"episode.open: {r}"
        sid = r["session"]
        for _ in range(4 * MAX_STEPS):
            s = c.request("episode.step", session=sid, action=0)
            assert s.get("ok"), f"episode.step: {s}"
            if s["done"]:
                return s
        raise AssertionError("interactive episode never finished")


def _netsim_client(port, proto, k):
    with ServeClient("127.0.0.1", port) as c:
        r = c.request("netsim.query", protocol=proto, k=k, n_nodes=5,
                      activations=300, seed=1)
        assert r.get("ok"), f"netsim.query: {r}"
        assert len(r["rewards"]) >= 5 and r["progress"] > 0, r
        return r


def _break_even_client(port, alpha):
    with ServeClient("127.0.0.1", port) as c:
        r = c.request("break_even.revenue", protocol="nakamoto",
                      policy="eyal-sirer-2014", alpha=alpha, gamma=0.5,
                      reps=4, episode_len=MAX_STEPS)
        assert r.get("ok"), f"break_even.revenue: {r}"
        assert 0.0 <= r["revenue"] <= 1.0, r
        return r


def _stats(port):
    with ServeClient("127.0.0.1", port) as c:
        r = c.request("stats")
        assert r.get("ok"), r
        return r


def _mixed_load(port):
    jobs = []
    with ThreadPoolExecutor(max_workers=N_CLIENTS) as pool:
        for i in range(12):
            policy = ("ppo", "honest", "eyal-sirer-2014")[i % 3]
            jobs.append(pool.submit(_policy_client, port, policy, i))
        for _ in range(8):
            jobs.append(pool.submit(_policy_client, port, "ppo", None))
        for i in range(8):
            jobs.append(pool.submit(_interactive_client, port, 100 + i))
        jobs.append(pool.submit(_netsim_client, port, "nakamoto", 1))
        jobs.append(pool.submit(_netsim_client, port, "bk", 2))
        jobs.append(pool.submit(_break_even_client, port, 0.25))
        jobs.append(pool.submit(_break_even_client, port, 0.35))
        for j in jobs:
            j.result()
    return len(jobs)


def _flood_worker(port, seeds):
    """One persistent connection running sequential seeded episodes —
    the shape of a real client, and it keeps per-episode TCP churn out
    of the throughput window."""
    with ServeClient("127.0.0.1", port) as c:
        for s in seeds:
            r = c.request("episode.run", policy="honest", seed=s)
            assert r.get("ok"), f"flood episode.run(seed={s}): {r}"


def _flood(port):
    """Full-occupancy sustained load: 2x lanes of always-outstanding
    policy sessions, so every burst backfills from a non-empty queue."""
    before = _stats(port)["report"]
    per = FLOOD_EPISODES // N_CLIENTS
    with ThreadPoolExecutor(max_workers=N_CLIENTS) as pool:
        jobs = [pool.submit(_flood_worker, port,
                            range(1000 + w * per, 1000 + (w + 1) * per))
                for w in range(N_CLIENTS)]
        for j in jobs:
            j.result()
    after = _stats(port)["report"]
    d_steps = after["steps"] - before["steps"]
    d_busy = after["busy_s"] - before["busy_s"]
    if d_steps <= 0 or d_busy <= 0:
        raise SystemExit(f"flood measured nothing (d_steps={d_steps}, "
                         f"d_busy={d_busy:.3f}s)")
    return d_steps / d_busy, after


def _baseline_steps_per_sec():
    """Equivalent batch rollout() on the same env/params/policy shape:
    LANES keys vmapped, honest policy, best of 3 timed dispatches."""
    import jax
    import jax.numpy as jnp

    from cpr_tpu.envs import registry
    from cpr_tpu.params import make_params

    env = registry.get_sized("nakamoto", MAX_STEPS)
    params = make_params(alpha=0.25, gamma=0.5, max_steps=MAX_STEPS)
    policy = env.policies["honest"]

    def batch(keys):
        return jax.vmap(
            lambda k: env.rollout(k, params, policy, BASELINE_STEPS)
        )(keys)

    run = jax.jit(batch)
    keys = jax.vmap(jax.random.PRNGKey)(
        jnp.arange(LANES, dtype=jnp.uint32))
    jax.block_until_ready(run(keys))  # compile outside the timing
    best = float("inf")
    for _ in range(3):
        t0 = telemetry.now()
        jax.block_until_ready(run(keys))
        best = min(best, telemetry.now() - t0)
    return LANES * BASELINE_STEPS / best


def _serve_events(trace, action=None):
    out = []
    with open(trace) as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") == "event" and e.get("name") == "serve" \
                    and (action is None or e.get("action") == action):
                out.append(e)
    return out


def _validate_stream(trace):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, trace, "--validate",
         "--expect", "serve,device_metrics,request"],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


def _check_drain_latency(trace):
    """The drain report's SLO summary must be present and sane:
    0 < p50 <= p99 < the wall budget (an episode.run total can never
    exceed the run itself)."""
    reports = _serve_events(trace, "report")
    detail = (reports[-1].get("detail") or {}) if reports else {}
    p50, p99 = detail.get("p50_s"), detail.get("p99_s")
    if not (isinstance(p50, (int, float)) and isinstance(p99, (int, float))):
        raise SystemExit(f"drain report carries no p50_s/p99_s: "
                         f"{sorted(detail)}")
    if not 0.0 < p50 <= p99 < WALL_S:
        raise SystemExit(f"drain report latencies insane: "
                         f"p50={p50} p99={p99}")
    return p50, p99


def _check_stitch(server_trace, client_trace):
    """trace_stitch must pair server and client request events under
    one shared run id — at least one two-sided trace with a full
    breakdown (queue/burst from the server side, reply from both)."""
    tools_dir = os.path.dirname(os.path.abspath(__file__))
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import trace_stitch

    st = trace_stitch.stitch([server_trace, client_trace])
    if len(st["runs"]) != 1:
        raise SystemExit(f"expected one shared run id across streams, "
                         f"got {sorted(st['runs'])}")
    paired = [t for t in st["traces"] if t["orphan"] is None]
    if not paired:
        raise SystemExit("trace_stitch paired no request across the "
                         "server and client streams")
    full = [t for t in paired
            if t["breakdown"]["burst_s"] is not None
            and t["breakdown"]["reply_s"] is not None]
    if not full:
        raise SystemExit("no paired trace carries a full critical-path "
                         "breakdown")
    return len(paired), len(st["traces"])


# ledger metrics the smoke must bank from the drain report; latencies
# gate with the flipped lower-is-better band (cpr_tpu/perf/gate.py)
_REQUIRED_METRICS = ("serve_steps_per_sec", "serve_p50_s", "serve_p99_s")


def _bank_and_gate(workdir, trace):
    ledger = Ledger(os.path.join(workdir, "perf_ledger.jsonl"))
    n = ledger.ingest_trace(trace)
    records = ledger.records()
    results = []
    for metric in _REQUIRED_METRICS:
        rows = [r for r in records if r.get("metric") == metric]
        if not rows:
            raise SystemExit(f"no {metric} row reached the ledger")
        results.extend(gate_row(r, records) for r in rows)
    summary = gate_summary(results)
    if not summary["ok"]:
        raise SystemExit(f"serve perf gate failed: {results}")
    sps = [r for r in records
           if r.get("metric") == "serve_steps_per_sec"]
    return n, sps[-1]["value"], summary


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-serve-smoke"
    os.makedirs(work, exist_ok=True)
    trace = os.path.join(work, "serve.jsonl")
    client_trace = os.path.join(work, "client.jsonl")
    for p in (trace, client_trace):
        if os.path.exists(p):
            os.remove(p)
    # the smoke's own client side is a telemetry producer too: every
    # ServeClient.request lands a role="client" request event on this
    # stream, and the manifest stamps the run id the server child
    # inherits via $CPR_RUN_ID — the two files trace_stitch pairs up
    telemetry.configure(client_trace)
    telemetry.current().manifest(dict(role="serve-smoke-client"))

    import jax

    jax.config.update("jax_platforms", "cpu")
    from cpr_tpu.envs import registry

    env = registry.get_sized("nakamoto", MAX_STEPS)
    snap = os.path.join(work, "policy.msgpack")
    _write_snapshot(snap, env)
    _log(f"snapshot written: {snap}")

    started = threading.Event()
    box = {}

    def on_start(proc):
        box["proc"] = proc
        started.set()

    def supervise():
        box["attempt"] = supervisor.run_child(
            _child_cmd(work, snap), wall_timeout_s=WALL_S, quiet_s=20.0,
            heartbeat_s=1.0, env=_child_env(work, trace), cwd=ROOT,
            on_start=on_start)

    child = threading.Thread(target=supervise)
    child.start()
    try:
        if not started.wait(30.0):
            raise SystemExit("run_child never spawned the server")
        ready = _wait_ready(os.path.join(work, "ready.json"), box["proc"])
        port = ready["port"]
        _log(f"server ready on port {port} (pid {ready['pid']})")

        n_jobs = _mixed_load(port)
        _log(f"mixed phase: {n_jobs} concurrent clients over "
             f"policy/interactive/netsim/break-even endpoints all ok")
        serve_sps, report = _flood(port)
        _log(f"flood phase: {FLOOD_EPISODES} episodes, sustained "
             f"{serve_sps:,.0f} steps/s (session total: "
             f"{report['steps']} steps, occupancy {report['occupancy']:.2f})")

        box["proc"].send_signal(signal.SIGTERM)
    except BaseException:
        # don't leave an orphaned server burning the wall budget
        proc = box.get("proc")
        if proc is not None and proc.poll() is None:
            proc.kill()
        raise
    child.join(120.0)
    if child.is_alive():
        raise SystemExit("server child did not drain within 120s")
    attempt = box["attempt"]
    if attempt.status != "ok" or attempt.rc != 0:
        raise SystemExit(f"server child did not exit cleanly after "
                         f"SIGTERM (status={attempt.status} "
                         f"rc={attempt.rc})")
    _log("SIGTERM drained cleanly (child exit 0)")

    for want in ("start", "admit", "complete", "query", "heartbeat",
                 "drain", "report", "stop"):
        if not _serve_events(trace, want):
            raise SystemExit(f"no serve '{want}' event in the trace")
    _validate_stream(trace)
    p50, p99 = _check_drain_latency(trace)
    _log(f"drain report SLO: p50={p50 * 1e3:.1f}ms p99={p99 * 1e3:.1f}ms")
    telemetry.configure(None)  # close the client sink before stitching
    paired, total = _check_stitch(trace, client_trace)
    _log(f"trace_stitch: {paired}/{total} request traces paired "
         f"across the server and client streams")

    baseline_sps = _baseline_steps_per_sec()
    min_frac = float(os.environ.get("CPR_SERVE_MIN_FRAC", "0.8"))
    frac = serve_sps / baseline_sps
    _log(f"throughput: serve {serve_sps:,.0f} vs batch rollout "
         f"{baseline_sps:,.0f} steps/s ({frac:.1%}, floor {min_frac:.0%})")
    if frac < min_frac:
        raise SystemExit(
            f"sustained serve throughput {serve_sps:,.0f} steps/s is "
            f"below {min_frac:.0%} of the equivalent batch rollout "
            f"({baseline_sps:,.0f} steps/s)")

    n_banked, banked_sps, summary = _bank_and_gate(work, trace)
    print(f"serve-smoke: PASS (serve {serve_sps:,.0f} steps/s = "
          f"{frac:.1%} of rollout baseline; p50 {p50 * 1e3:.1f}ms / "
          f"p99 {p99 * 1e3:.1f}ms; {paired} stitched traces; banked "
          f"{n_banked} ledger rows incl. serve_steps_per_sec="
          f"{banked_sps:,.0f}; gate {summary})")


if __name__ == "__main__":
    main()
