"""jaxlint CLI — the repo's JAX-aware static-analysis gate.

    python tools/jaxlint.py cpr_tpu tools                # human output
    python tools/jaxlint.py cpr_tpu tools --format json  # machine output
    python tools/jaxlint.py --list-rules                 # rule catalog

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = usage error.  Rule catalog and rationale: docs/ANALYSIS.md.

The implementation lives in cpr_tpu/analysis/; this wrapper loads that
package WITHOUT executing cpr_tpu/__init__.py (which imports jax via
params), so linting never initializes a JAX backend — pure AST, ~1s
over the whole repo on the 1-core host, safe to run anywhere including
hosts with a wedged TPU plugin.
"""

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Import cpr_tpu.analysis as a package but bypass cpr_tpu's
    __init__ (keeps the CLI jax-free; tests assert this)."""
    if "cpr_tpu.analysis" in sys.modules:
        return sys.modules["cpr_tpu.analysis"]
    pkg_dir = os.path.join(REPO, "cpr_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "cpr_tpu.analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["cpr_tpu.analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="jaxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint "
                         "(default: cpr_tpu tools)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="stdout format (json: stable rule ids, one "
                         "object with findings + rule catalog)")
    ap.add_argument("--disable", action="append", default=[],
                    metavar="RULE[,RULE]",
                    help="skip rule id(s); repeatable")
    ap.add_argument("--baseline", metavar="FILE",
                    help="JSON baseline of grandfathered findings "
                         "(gate fails only on NEW findings)")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write current findings as a baseline and "
                         "exit 0")
    ap.add_argument("--output", metavar="FILE",
                    help="also write the JSON report to FILE "
                         "(regardless of --format)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    analysis = _load_analysis()
    from cpr_tpu.analysis.rules import RULES

    if args.list_rules:
        for r in RULES:
            print(f"{r.id:<14} {r.summary}")
        return 0

    paths = args.paths or ["cpr_tpu", "tools"]
    disable = [r for part in args.disable for r in part.split(",") if r]
    baseline = None
    if args.baseline:
        try:
            baseline = analysis.load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"jaxlint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
    try:
        findings = analysis.run_lint(paths, root=REPO, disable=disable,
                                     baseline=baseline)
    except ValueError as e:
        print(f"jaxlint: {e}", file=sys.stderr)
        return 2

    report = {
        "tool": "jaxlint",
        "version": 1,
        "paths": paths,
        "disabled": sorted(disable),
        "baseline": args.baseline,
        "rules": [{"id": r.id, "summary": r.summary} for r in RULES
                  if r.id not in disable],
        "findings": [f.as_dict() for f in findings],
    }
    if args.write_baseline:
        # lint reports are regenerable scratch, and this CLI must stay
        # importable without the package (resilience.atomic_write_*
        # pulls jax via cpr_tpu/__init__)
        # jaxlint: disable-next-line=raw-write
        with open(args.write_baseline, "w") as f:
            json.dump({"findings": [f_.as_dict() for f_ in findings]},
                      f, indent=2)
            f.write("\n")
        print(f"jaxlint: wrote baseline with {len(findings)} "
              f"finding(s) to {args.write_baseline}", file=sys.stderr)
        return 0
    if args.output:
        # jaxlint: disable-next-line=raw-write — scratch-report exemption
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    if args.format == "json":
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        for f_ in findings:
            print(f"{f_.path}:{f_.line}:{f_.col}: "
                  f"[{f_.rule}] {f_.message}")
        suffix = " (new vs baseline)" if baseline else ""
        print(f"jaxlint: {len(findings)} finding(s){suffix} in "
              f"{len(paths)} path(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
