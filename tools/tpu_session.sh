#!/bin/bash
# One healthy-chip window, spent in priority order (round-2 lesson:
# bank the bench BEFORE anything that can wedge the backend).
#   1. headline bench  -> BENCH_self_${ROUND}.json   (the evidence artifact)
#   2. configs 2-4     -> BENCH_CONFIGS_tpu_${ROUND}.json
#   3. PRNG sweep      -> stdout tee            (read-only perf data)
#   4. VI bisect       -> LAST: its candidates have crashed the worker
# Each step is already watchdogged internally (bench.py subprocess
# pattern / bisect per-candidate children).  Artifacts are written via
# temp files and only promoted on success with a tpu backend tag, so a
# failed or CPU-fallback run never clobbers banked evidence.
set -u -o pipefail
cd "$(dirname "$0")/.."
ROUND=${CPR_ROUND:-r04}
log=tools/tpu_session.log
echo "=== tpu session $(date +%F_%T) ===" | tee -a "$log"

echo "--- 1. headline bench" | tee -a "$log"
if python bench.py >/tmp/bench_line.json 2>>"$log"; then
  tee -a "$log" </tmp/bench_line.json
  if grep -q '"backend": "\(tpu\|axon\)"' /tmp/bench_line.json; then
    mv /tmp/bench_line.json BENCH_self_${ROUND}.json
    echo "banked BENCH_self_${ROUND}.json" | tee -a "$log"
  else
    echo "NOT banked: backend is not tpu" | tee -a "$log"
  fi
else
  echo "bench failed rc=$?" | tee -a "$log"
fi

echo "--- 2. configs 2-4" | tee -a "$log"
# bank only if EVERY row is on-chip (rows now carry per-config backend
# tags, so a single tpu row must not bank a partially-CPU artifact);
# drop any stale artifact first so a crashed run can't re-bank it
rm -f BENCH_CONFIGS.json
if python bench.py --configs 2>>"$log" | tee -a "$log" \
   && python -c 'import json,sys; rows=json.load(open("BENCH_CONFIGS.json")); sys.exit(0 if rows and all(r.get("backend") in ("tpu","axon") for r in rows) else 1)'; then
  cp -f BENCH_CONFIGS.json BENCH_CONFIGS_tpu_${ROUND}.json
  echo "banked BENCH_CONFIGS_tpu_${ROUND}.json" | tee -a "$log"
else
  echo "configs NOT banked (failed or cpu fallback)" | tee -a "$log"
fi

echo "--- 3. PRNG sweep" | tee -a "$log"
timeout 900 python tools/tpu_bench_experiments.py 2>>"$log" | tee -a "$log"

echo "--- 4. VI bisect (may wedge the chip; runs last)" | tee -a "$log"
python tools/tpu_vi_bisect.py 2>>"$log" | tee -a "$log"

echo "=== done $(date +%F_%T) ===" | tee -a "$log"
