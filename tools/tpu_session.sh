#!/bin/bash
# One healthy-chip window, spent in priority order (round-2 lesson:
# bank the bench BEFORE anything that can wedge the backend).
#   1. headline bench  -> BENCH_self_${ROUND}.json   (the evidence artifact)
#   2. configs 2-4     -> BENCH_CONFIGS_tpu_${ROUND}.json  (active-set rows)
#   3. scaling curve   -> BENCH_SCALING_${ROUND}.json (VERDICT r4 #2)
#   4. PPO training    -> runs/${ROUND}-tailstorm-a45/  (VERDICT r4 #3)
#   5. capstone VI     -> docs/CAPSTONE timing with Anderson (VERDICT r4 #7)
# Each step is already watchdogged internally (bench.py subprocess
# pattern / per-point children).  Artifacts are written via temp files
# and only promoted on success with a tpu backend tag, so a failed or
# CPU-fallback run never clobbers banked evidence.
set -u -o pipefail
cd "$(dirname "$0")/.."
ROUND=${CPR_ROUND:-r05}
log=tools/tpu_session.log
echo "=== tpu session $(date +%F_%T) ===" | tee -a "$log"

echo "--- 1. headline bench" | tee -a "$log"
if python bench.py >/tmp/bench_line.json 2>>"$log"; then
  tee -a "$log" </tmp/bench_line.json
  if grep -q '"backend": "\(tpu\|axon\)"' /tmp/bench_line.json; then
    mv /tmp/bench_line.json BENCH_self_${ROUND}.json
    echo "banked BENCH_self_${ROUND}.json" | tee -a "$log"
  else
    echo "NOT banked: backend is not tpu" | tee -a "$log"
  fi
else
  echo "bench failed rc=$?" | tee -a "$log"
fi

echo "--- 2. configs 2-4" | tee -a "$log"
# bank only if EVERY row is on-chip (rows now carry per-config backend
# tags, so a single tpu row must not bank a partially-CPU artifact);
# drop any stale artifact first so a crashed run can't re-bank it
rm -f BENCH_CONFIGS.json
if python bench.py --configs 2>>"$log" | tee -a "$log" \
   && python -c 'import json,sys; rows=json.load(open("BENCH_CONFIGS.json")); sys.exit(0 if rows and all(r.get("backend") in ("tpu","axon") for r in rows) else 1)'; then
  cp -f BENCH_CONFIGS.json BENCH_CONFIGS_tpu_${ROUND}.json
  echo "banked BENCH_CONFIGS_tpu_${ROUND}.json" | tee -a "$log"
else
  echo "configs NOT banked (failed or cpu fallback)" | tee -a "$log"
fi

echo "--- 3. batch-scaling curve" | tee -a "$log"
timeout 3600 python tools/tpu_scaling_curve.py 2>>"$log" | tee -a "$log"

echo "--- 4. PPO training (collapse-protected, VERDICT r4 #3)" | tee -a "$log"
timeout 5400 python examples/train_ppo.py \
  cpr_tpu/train/configs/tailstorm-8-discount-a45-r5.yaml \
  runs/${ROUND}-tailstorm-a45 800 2>>"$log" | tee -a "$log" \
  || echo "training step failed/timeout" | tee -a "$log"
# trained-policy per-alpha model table from the FINAL policy (the
# verdict's done-criterion is the LAST checkpoint, not a rescued peak)
if [ -f runs/${ROUND}-tailstorm-a45/last-model.msgpack ]; then
  timeout 1800 python examples/rl_eval_study.py \
    tailstorm-8-discount-heuristic \
    runs/${ROUND}-tailstorm-a45/last-model.msgpack \
    cpr_tpu/train/configs/tailstorm-8-discount-a45-r5.yaml \
    > runs/${ROUND}-tailstorm-a45/rl_eval_model_table.tsv \
    2>>"$log" && echo "banked rl_eval_model_table.tsv" | tee -a "$log"
fi

echo "--- 5. GhostDAG capstone (Anderson-accelerated)" | tee -a "$log"
timeout 2400 python examples/solve_ghostdag_mdp.py 8 2>>"$log" | tee -a "$log" \
  || echo "capstone failed/timeout" | tee -a "$log"

echo "=== done $(date +%F_%T) ===" | tee -a "$log"
