"""Stitch the telemetry JSONL streams of one run into a trace tree.

A served run writes several event streams: the serve server (often a
supervisor child), the supervising parent, and any number of clients
(tools/serve_smoke.py writes one for its client side).  Timestamps are
`perf_counter` — process-relative, never comparable across files — so
correlation is by ids: every stream's manifest carries the shared
`run` id (minted once, inherited via $CPR_RUN_ID), and every schema-v8
`request` event carries the per-request `trace_id` that the protocol's
reserved `_trace` frame field ferries across the wire.  This tool
merges the streams, pairs each trace's server and client sides, and
prints a per-request critical-path breakdown built from durations
only:

    route   router total_s minus server total_s — the fleet hop
            (forwarding + replica queue pickup; absent without a
            router stream, i.e. every single-process trace)
    queue   server queue_wait_s minus the admission splice
    splice  device-program admission splice (server splice_s)
    burst   server-side service time (device bursts / ticks)
    reply   client total_s minus server total_s (minus the route hop
            when a router sat between them) — wire + framing +
            asyncio handoff (needs both sides; "-" on orphans)

Role "router" events (schema v9, cpr_tpu/serve/router.py) are an
optional third side: traces with one gain the route segment, traces
without one keep the exact two-sided breakdown, so the tool works
unchanged on single-process serve runs.

A trace seen on only one side is an *orphan* — expected for streams
captured mid-run (a client stream without the server's, a request
completed after the server stream was cut) — and is kept, marked, and
tallied rather than dropped.  A router-only trace counts as orphaned
too (no server side to split against).

Usage: python tools/trace_stitch.py server.jsonl client.jsonl ...
           [--op PREFIX] [--limit N] [--json]

Exit codes: 0 = stitched something, 1 = no request events found,
2 = usage/IO error.
"""

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def read_stream(path: str) -> dict:
    """One JSONL stream -> its run ids (manifests + request extras) and
    `request` events, each stamped with the stream name and its line
    order (the only cross-event order that exists within a stream).
    Typed point events other than `request` (alert, route, admission,
    ... — schema v14 grows them) carry no trace_id and can never pair
    into a trace: they are tolerated and tallied per name as
    `unpaired`, never treated as malformed."""
    name = os.path.basename(path)
    runs, requests, n = [], [], 0
    unpaired: dict = defaultdict(int)
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if not isinstance(e, dict):
                continue
            n += 1
            if e.get("kind") == "manifest" and e.get("run"):
                if e["run"] not in runs:
                    runs.append(e["run"])
            elif e.get("kind") == "event" and e.get("name") == "request":
                requests.append(dict(e, _stream=name, _line=i))
            elif e.get("kind") == "event" and e.get("name"):
                unpaired[str(e["name"])] += 1
    return {"path": path, "name": name, "runs": runs,
            "requests": requests, "n_events": n,
            "unpaired": dict(unpaired)}


def _num(v):
    return float(v) if isinstance(v, (int, float)) else None


def _breakdown(server: dict | None, client: dict | None,
               router: dict | None = None) -> dict:
    """Durations-only critical path of one request.  Every component
    is None when the side that measures it is missing; the route hop
    exists only when a router stream was stitched in."""
    s_total = _num(server.get("total_s")) if server else None
    c_total = _num(client.get("total_s")) if client else None
    r_total = _num(router.get("total_s")) if router else None
    queue = splice = burst = reply = route = None
    if server:
        wait = _num(server.get("queue_wait_s"))
        splice = _num(server.get("splice_s"))
        burst = _num(server.get("service_s"))
        if wait is not None:
            queue = max(0.0, wait - (splice or 0.0))
    if r_total is not None and s_total is not None:
        route = max(0.0, r_total - s_total)
    if c_total is not None:
        # the reply leg is the client wall past the furthest-upstream
        # total we have: router if present, else the server's
        upstream = r_total if r_total is not None else s_total
        if upstream is not None:
            reply = max(0.0, c_total - upstream)
    return {"route_s": route, "queue_s": queue, "splice_s": splice,
            "burst_s": burst, "reply_s": reply,
            "total_s": (c_total if c_total is not None
                        else r_total if r_total is not None
                        else s_total)}


def stitch(paths) -> dict:
    """Merge streams and pair request events by trace_id.  Returns
    {streams, runs, traces, ops, orphans}; `traces` is a list of
    {trace_id, run, op, status, server, client, orphan, breakdown}
    in first-seen order; `ops` aggregates count / two-sided count /
    orphan count / total-latency sum+max per op."""
    streams = [read_stream(p) for p in paths]
    runs: dict[str, list[str]] = {}
    for st in streams:
        for rid in st["runs"]:
            runs.setdefault(rid, []).append(st["name"])
    by_id: dict[str, dict] = {}
    order: list[str] = []
    for st in streams:
        for e in st["requests"]:
            tid = str(e.get("trace_id") or f"?{st['name']}:{e['_line']}")
            t = by_id.get(tid)
            if t is None:
                t = by_id[tid] = {"trace_id": tid, "run": None,
                                  "op": None, "status": None,
                                  "server": None, "client": None,
                                  "router": None}
                order.append(tid)
            role = str(e.get("role") or "unknown")
            side = ("server" if role == "server"
                    else "router" if role == "router" else "client")
            if t[side] is None:  # duplicate events keep the first
                t[side] = e
            if t["run"] is None and e.get("run"):
                t["run"] = e["run"]
            if t["op"] is None and e.get("op") is not None:
                t["op"] = str(e["op"])
            # the server's verdict wins (the client may see "error"
            # where the server refused); else first seen
            if side == "server" or t["status"] is None:
                t["status"] = e.get("status")
    traces = []
    ops = defaultdict(lambda: {"n": 0, "two_sided": 0, "orphans": 0,
                               "sum_total_s": 0.0, "max_total_s": 0.0})
    for tid in order:
        t = by_id[tid]
        orphan = (None if t["server"] and t["client"]
                  else "no-server" if t["client"] else "no-client")
        bd = _breakdown(t["server"], t["client"], t["router"])
        traces.append(dict(t, orphan=orphan, breakdown=bd))
        a = ops[t["op"] or "?"]
        a["n"] += 1
        a["two_sided"] += orphan is None
        a["orphans"] += orphan is not None
        if bd["total_s"] is not None:
            a["sum_total_s"] += bd["total_s"]
            a["max_total_s"] = max(a["max_total_s"], bd["total_s"])
    unpaired: dict = defaultdict(int)
    for s in streams:
        for nm, c in s["unpaired"].items():
            unpaired[nm] += c
    return {"streams": [{"name": s["name"], "path": s["path"],
                         "runs": s["runs"], "n_events": s["n_events"],
                         "n_requests": len(s["requests"]),
                         "unpaired": s["unpaired"]}
                        for s in streams],
            "runs": runs,
            "traces": traces,
            "ops": dict(sorted(ops.items())),
            "orphans": sum(1 for t in traces if t["orphan"]),
            "unpaired": dict(sorted(unpaired.items()))}


def _fmt_s(v) -> str:
    return f"{v:.4f}s" if isinstance(v, (int, float)) else "-"


def render(st: dict, out=sys.stdout, limit: int | None = None):
    for s in st["streams"]:
        runs = ",".join(s["runs"]) or "-"
        print(f"stream {s['name']}: {s['n_events']} events, "
              f"{s['n_requests']} requests, run={runs}", file=out)
    for rid, names in sorted(st["runs"].items()):
        print(f"run {rid}: {len(names)} streams "
              f"({', '.join(sorted(set(names)))})", file=out)
    if st.get("unpaired"):
        # typed point events with no trace side (alert, route, ...):
        # tallied so a stream full of v14 alerts reads as health
        # signal, not as stitching loss
        tally = " ".join(f"{nm}={c}"
                         for nm, c in st["unpaired"].items())
        print(f"unpaired typed events: {tally}", file=out)
    shown = st["traces"] if limit is None else st["traces"][:limit]
    for t in shown:
        bd = t["breakdown"]
        side = ("both" if t["orphan"] is None
                else f"orphan:{t['orphan']}")
        print(f"\ntrace {t['trace_id']}  op={t['op']} "
              f"status={t['status']} [{side}] "
              f"total={_fmt_s(bd['total_s'])}", file=out)
        sess = (t["server"] or {}).get("session") \
            or (t["client"] or {}).get("session")
        lane = (t["server"] or {}).get("lane")
        ctx = " ".join(p for p in (
            f"session={sess}" if sess is not None else "",
            f"lane={lane}" if lane is not None else "") if p)
        if ctx:
            print(f"  {ctx}", file=out)
        if bd.get("route_s") is not None:
            print(f"  route   {_fmt_s(bd['route_s'])}", file=out)
        print(f"  queue   {_fmt_s(bd['queue_s'])}", file=out)
        print(f"  splice  {_fmt_s(bd['splice_s'])}", file=out)
        print(f"  burst   {_fmt_s(bd['burst_s'])}", file=out)
        print(f"  reply   {_fmt_s(bd['reply_s'])}", file=out)
    if limit is not None and len(st["traces"]) > limit:
        print(f"\n... {len(st['traces']) - limit} more traces "
              f"(--limit)", file=out)
    print(f"\n{'op':<20} {'n':>6} {'two-sided':>9} {'orphans':>8} "
          f"{'mean_s':>9} {'max_s':>9}", file=out)
    for op, a in st["ops"].items():
        mean = a["sum_total_s"] / a["n"] if a["n"] else 0.0
        print(f"{op:<20} {a['n']:>6} {a['two_sided']:>9} "
              f"{a['orphans']:>8} {mean:>9.4f} "
              f"{a['max_total_s']:>9.4f}", file=out)
    print(f"stitched {len(st['traces'])} traces, "
          f"{st['orphans']} orphaned", file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_stitch", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("streams", nargs="*", metavar="JSONL",
                    help="telemetry streams of one run (server, "
                         "supervisor child, clients — any order)")
    ap.add_argument("--run", metavar="RUN_ID",
                    help="resolve ALL of the run's archived telemetry "
                         "streams through the run archive "
                         "(cpr_tpu.perf.archive) instead of naming "
                         "paths")
    ap.add_argument("--archive", metavar="DIR",
                    help="archive root for --run (default: "
                         "$CPR_OBS_ARCHIVE or runs/archive)")
    ap.add_argument("--op", metavar="PREFIX",
                    help="only traces whose op starts with PREFIX")
    ap.add_argument("--limit", type=int, metavar="N",
                    help="print at most N trace trees (summary still "
                         "covers everything)")
    ap.add_argument("--json", action="store_true",
                    help="dump the stitched structure as JSON instead "
                         "of the text tree")
    args = ap.parse_args(argv)
    if args.run:
        # archive resolution: every telemetry stream the run archived
        # (server + supervisor + clients), not just the primary — the
        # stitcher's whole point is the multi-stream view
        from cpr_tpu.perf import archive
        rec = archive.load_run(args.run, root=args.archive)
        if rec is None:
            print(f"trace_stitch: run {args.run!r} not found in "
                  f"archive {archive.archive_dir(args.archive)!r}",
                  file=sys.stderr)
            return 2
        args.streams = list(args.streams) + archive.run_streams(rec)
    if not args.streams:
        ap.error("no streams: name JSONL paths or pass --run RUN_ID")
    try:
        st = stitch(args.streams)
    except OSError as e:
        print(f"trace_stitch: {e}", file=sys.stderr)
        return 2
    if args.op:
        st["traces"] = [t for t in st["traces"]
                        if str(t["op"] or "").startswith(args.op)]
        st["ops"] = {op: a for op, a in st["ops"].items()
                     if op.startswith(args.op)}
        st["orphans"] = sum(1 for t in st["traces"] if t["orphan"])
    if args.json:
        print(json.dumps(st, indent=2, sort_keys=True, default=str))
    else:
        render(st, limit=args.limit)
    return 0 if any(s["n_requests"] for s in st["streams"]) else 1


if __name__ == "__main__":
    sys.exit(main())
