"""Kill-and-resume determinism smoke (`make resilience-smoke`).

Three tiny CPU training runs of one config, in subprocesses so an
injected kill dies exactly like a real crash:

  A  — uninterrupted reference, with `io_error@checkpoint=1` injected
       so the checkpoint-write retry path is exercised and proven
       harmless to the result;
  B1 — `kill@update=K` injected: the child crashes mid-run, leaving a
       snapshot plus orphan metrics rows the snapshot never saw;
  B2 — resumed from B's snapshot (fault injection off), runs to the
       end.

Asserts the acceptance criterion of docs/RESILIENCE.md: B1 exited
nonzero, B2 completed, B's concatenated metrics.jsonl — headers and
volatile timing keys stripped (resilience.metrics_fingerprint) — is
bit-identical to A's, and update numbering carries no duplicates.
Both telemetry streams must then pass
`tools/trace_summary.py --validate --expect <resilience events>`.

Usage: python tools/resilience_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from cpr_tpu import resilience  # noqa: E402  (jax-free at import)

TOTAL_UPDATES = 8
KILL_AT = 8          # snapshot cadence 3 -> last snapshot at 6, update 7
SNAP_FREQ = 3        # becomes an orphan row that resume must trim
CFG = dict(
    protocol="nakamoto", alpha=0.25, gamma=0.5, episode_len=8,
    n_envs=4, total_updates=TOTAL_UPDATES, seed=0,
    ppo=dict(n_steps=4, n_minibatches=2, update_epochs=1,
             layer_size=8, n_layers=1),
    eval=dict(freq=3, start_at_iteration=0, episodes_per_alpha=2),
)


def _child(out_dir: str, resume: bool):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from cpr_tpu.train.config import TrainConfig
    from cpr_tpu.train.driver import train_from_config

    train_from_config(TrainConfig(**CFG), out_dir=out_dir,
                      resume=resume, snapshot_freq=SNAP_FREQ)


def _run_child(out_dir: str, telemetry_path: str, *, resume=False,
               fault=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CPR_TELEMETRY=telemetry_path)
    env.pop(resilience.FAULT_ENV_VAR, None)
    if fault:
        env[resilience.FAULT_ENV_VAR] = fault
    cmd = [sys.executable, os.path.abspath(__file__), "--child", out_dir]
    if resume:
        cmd.append("--resume")
    return subprocess.run(cmd, env=env, capture_output=True, text=True)


def _validate_stream(path: str, expect: str):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, path, "--validate", "--expect", expect],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {path}")


def main():
    if "--child" in sys.argv:
        _child(sys.argv[sys.argv.index("--child") + 1],
               "--resume" in sys.argv)
        return
    work = (sys.argv[1] if len(sys.argv) > 1
            else "/tmp/cpr-resilience-smoke")
    os.makedirs(work, exist_ok=True)
    a_dir, b_dir = os.path.join(work, "a"), os.path.join(work, "b")
    a_tele, b_tele = (os.path.join(work, "a.jsonl"),
                      os.path.join(work, "b.jsonl"))

    print("resilience-smoke: run A (uninterrupted, io_error injected "
          "on checkpoint 1)", file=sys.stderr)
    r = _run_child(a_dir, a_tele, fault="io_error@checkpoint=1")
    if r.returncode != 0:
        sys.stderr.write(r.stderr)
        raise SystemExit("run A failed")

    print(f"resilience-smoke: run B1 (kill@update={KILL_AT})",
          file=sys.stderr)
    r = _run_child(b_dir, b_tele, fault=f"kill@update={KILL_AT}")
    if r.returncode == 0:
        raise SystemExit("run B1 was supposed to die from the injected "
                         "kill, but exited 0")
    if not os.path.exists(os.path.join(b_dir, "snapshot.msgpack")):
        sys.stderr.write(r.stderr)
        raise SystemExit("run B1 left no snapshot")

    print("resilience-smoke: run B2 (resume)", file=sys.stderr)
    r = _run_child(b_dir, b_tele, resume=True)
    if r.returncode != 0:
        sys.stderr.write(r.stderr)
        raise SystemExit("resume failed")

    fp_a = resilience.metrics_fingerprint(
        os.path.join(a_dir, "metrics.jsonl"))
    fp_b = resilience.metrics_fingerprint(
        os.path.join(b_dir, "metrics.jsonl"))
    if fp_a != fp_b:
        for i, (ra, rb) in enumerate(zip(fp_a, fp_b)):
            if ra != rb:
                print(f"first divergent row {i}:\n  A: {json.dumps(ra)}"
                      f"\n  B: {json.dumps(rb)}", file=sys.stderr)
                break
        raise SystemExit(
            f"kill-and-resume history diverged from the uninterrupted "
            f"run ({len(fp_a)} vs {len(fp_b)} rows)")
    updates = [row["update"] for row in fp_b if "eval" not in row
               and "revert" not in row and "update" in row]
    if updates != sorted(set(updates)):
        raise SystemExit(f"duplicate/unordered update rows: {updates}")

    _validate_stream(a_tele, "checkpoint,retry,fault_injected")
    _validate_stream(b_tele, "checkpoint,resume,fault_injected")
    print(f"resilience-smoke: PASS ({len(fp_a)} comparable rows, "
          f"updates 1..{TOTAL_UPDATES} bit-identical after "
          f"kill@update={KILL_AT} + resume)")


if __name__ == "__main__":
    main()
