"""Probe the per-call device-time ceiling hypothesis on the axon TPU.

Observation across the round-3 bisects: every successful device call
finished in <= ~76 s; every "UNAVAILABLE: TPU device error" came from a
call that would have run 100-165 s — regardless of which kernel it was
(ethereum scans at several shapes/policies, VI while_loops in round 2).
Hypothesis: the axon worker (or tunnel RPC) enforces a single-execution
deadline around ~90-120 s; long-running XLA programs are killed and
surface as device faults.

These candidates use PURE matmul scans (no cpr_tpu code): calibrate the
per-iteration cost, then run (a) a ~40 s call, (b) a ~150 s call, and
(c) the same total work as (b) split into five ~30 s calls.  If (a) and
(c) pass while (b) crashes, the ceiling is per-call device time — and
the framework-level fix is chunking long scans/solves across calls
(exactly what the chunked VI impl does).

Candidates run supervised (bisect_common -> cpr_tpu/supervisor): a
bounded device probe runs before the first candidate, and each
candidate is watchdog-bounded, so a wedged chip is detected in seconds
instead of burning the 420 s candidate timeout.

Usage: python tools/tpu_limit_probe.py [max_candidates]
"""

import sys

# run as a script from anywhere: the tools dir is sys.path[0] only for
# direct execution, so resolve it explicitly
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from bisect_common import run_candidates  # noqa: E402

CAL = """
import time
N = 4096
x0 = jax.random.normal(jax.random.PRNGKey(0), (N, N), jnp.float32) * 1e-3
def burn(x, iters):
    def body(c, _):
        return c @ c * 1e-3 + x * 1e-6, None
    out, _ = jax.lax.scan(body, x, None, length=iters)
    return out.sum()
b = jax.jit(burn, static_argnums=1)
# under axon, block_until_ready returns before execution completes
# (async dispatch over the tunnel) — only a value FETCH truly waits, so
# all timing here fetches the scalar
def timed(n):
    float(b(x0, n))  # warm (each static n compiles separately)
    t0 = time.time()
    v = float(b(x0, n))
    return time.time() - t0
per = timed(256) / 256
print(f"calibration: {per*1000:.2f} ms/iter (warm, fetched)", flush=True)
"""

CANDIDATES = [
    ("burn_40s_single_call", CAL + """
n = max(8, int(40.0 / per))
d = timed(n)
print(f"ok single {d:.0f}s device-time ({n} iters)")"""),
    ("burn_150s_five_calls", CAL + """
n = max(8, int(30.0 / per))
float(b(x0, n))  # warm
t0 = time.time()
for _ in range(5):
    float(b(x0, n))
d = time.time() - t0
print(f"ok split {d:.0f}s total (5 x {n} iters)")"""),
    # the hypothesized crasher runs LAST; its warm call IS the long call
    ("burn_150s_single_call", CAL + """
n = max(8, int(150.0 / per))
t0 = time.time()
float(b(x0, n))
d = time.time() - t0
print(f"ok single {d:.0f}s incl-compile ({n} iters)")"""),
]

if __name__ == "__main__":
    limit = int(sys.argv[1]) if len(sys.argv) > 1 else None
    run_candidates(CANDIDATES, limit, timeout=420.0)
