"""Adversary-in-the-network smoke (`make attack-smoke`).

Proves the netsim attack subsystem (docs/NETSIM.md, "Attacks under
real networks") end-to-end on the CPU CI host — sweep children run
under forced 1-device and 2-device XLA CPU meshes, so the lane-axis
sharding seam is exercised with no accelerator:

  1  per device count, a sweep child runs `attack_sweep` over a
     protocol x topology x alpha grid (nakamoto + an unsupported
     protocol on the 4-node clique) with alpha and policy as LANE
     inputs — the whole alphas x policies x reps batch is ONE vmapped
     (and, at 2 devices, lane-sharded) device program — the nakamoto
     rows must come back clean (full withholding row schema) and the
     unsupported protocol must degrade to a reason-tagged error row;
  2  lane parity: the reward columns of the sweep rows must be
     BIT-IDENTICAL between the 1-device and 2-device runs — same
     lanes, partitioned;
  3  an anchor child asserts the degenerate-network equivalence: on
     the zero-delay two-node clique, the netsim attacker's mean
     relative revenue per (policy, alpha) must match the two-party
     NakamotoSSZ env at gamma=0 within TOLERANCE (tier-1 proves 0.05
     at larger samples; the smoke's smaller samples get 0.06);
  4  a supervised `python -m cpr_tpu.serve.server` answers
     `netsim.attack_sweep` twice: the first sweep banks v11
     `attack_sweep` events, the repeat must come back `cached` with
     identical rows (the topology-fingerprint sweep cache), then the
     server drains clean on SIGTERM;
  5  every trace passes `trace_summary --validate --expect
     attack_sweep` (serve trace: `--expect serve,attack_sweep`), and
     the two same-shaped sweep traces ingest into one perf ledger:
     `attack_sweep_lanes_per_sec` rows must land at BOTH
     cfg_devices=1 and cfg_devices=2 with cfg_protocol/cfg_topology
     attached, and every banked row must clear the regression gate.
     (The anchor and serve traces are validated but not banked: their
     sweeps are correctness probes with different topology/lane
     shapes, exactly what the ledger's shape fingerprints keep out of
     each other's baselines.)

Usage: python tools/attack_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from cpr_tpu import supervisor  # noqa: E402
from cpr_tpu.perf.gate import gate_row, gate_summary  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402
from cpr_tpu.serve.protocol import ServeClient  # noqa: E402

DEVICES = 2                 # the forced virtual CPU mesh span
ALPHAS = (0.33, 0.45)
POLICIES = ("honest", "eyal-sirer-2014")
ACTIVATIONS = 600           # per sweep lane
REPS = 2                    # lanes/point: 2x2x2 = 8, shards evenly
TOLERANCE = 0.06            # degenerate anchor gap (tier-1: 0.05)
READY_TIMEOUT_S = 300.0
WALL_S = 900.0


def _log(msg):
    print(f"attack-smoke: {msg}", file=sys.stderr)


def _child_env(workdir, trace, extra=None, devices=1):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count="
                         f"{devices}",
               CPR_TELEMETRY=trace,
               CPR_TPU_CACHE=os.path.join(workdir, "cache"))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def _validate_stream(trace, expect):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, trace, "--validate", "--expect", expect],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


# one sweep child per device count: the clique-4 attack grid with rows
# dumped as JSON for the parent's cross-device bit-identity check
_SWEEP_CHILD = textwrap.dedent("""\
    import json, os

    import jax

    jax.config.update("jax_platforms", "cpu")

    from cpr_tpu import telemetry
    from cpr_tpu.netsim.attack import attack_sweep
    from cpr_tpu.network import symmetric_clique

    devices = int(os.environ["CPR_SMOKE_DEVICES"])
    alphas = tuple(float(a) for a in
                   os.environ["CPR_SMOKE_ALPHAS"].split(","))
    policies = tuple(os.environ["CPR_SMOKE_POLICIES"].split(","))
    activations = int(os.environ["CPR_SMOKE_ACTIVATIONS"])
    reps = int(os.environ["CPR_SMOKE_REPS"])

    mesh = None
    if devices > 1:
        from cpr_tpu.parallel import default_mesh
        devs = jax.devices()
        assert len(devs) >= devices, (len(devs), devices)
        mesh = default_mesh(devices=devs[:devices])

    tele = telemetry.current()
    tele.manifest(dict(role="attack-smoke-sweep", devices=devices,
                       activations=activations, reps=reps))

    net = symmetric_clique(4, activation_delay=30.0,
                           propagation_delay=1.0)
    rows = attack_sweep([("clique-4", net)],
                        protocols=(("nakamoto", {}), ("tailstorm", {})),
                        policies=policies, alphas=alphas,
                        activation_delays=(60.0,),
                        activations=activations, reps=reps, seed=11,
                        mesh=mesh)
    # the unsupported protocol degrades to exactly one reason-tagged
    # error row; the nakamoto half must be clean
    bad = [r for r in rows if "error" in r]
    assert len(bad) == 1 and bad[0]["protocol"] == "tailstorm", bad
    assert bad[0]["reason"] == "unsupported-protocol", bad
    rows = [r for r in rows if "error" not in r]
    need = {"protocol", "attack", "alpha", "gamma", "relative_reward",
            "reward_attacker", "reward_defender", "topology",
            "n_nodes", "engine"}
    for r in rows:
        assert need <= set(r), sorted(need - set(r))
        assert r["gamma"] == -1.0, r   # emerges from message racing
    print(f"sweep: {len(rows)} clean rows at {devices} device(s)")

    # timing differs per run; the physics must not
    for r in rows:
        r.pop("machine_duration_s", None)
    with open(os.environ["CPR_SMOKE_OUT"], "w") as f:
        json.dump(rows, f, sort_keys=True)
    print("attack sweep child ok:", devices, "device(s)")
""")


# the degenerate anchor: zero-delay two-node clique == two-party
# NakamotoSSZ env at gamma=0, within tolerance per (policy, alpha)
_ANCHOR_CHILD = textwrap.dedent("""\
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")

    from cpr_tpu import telemetry
    from cpr_tpu.experiments.withholding import withholding_rows
    from cpr_tpu.netsim.attack import attack_sweep
    from cpr_tpu.network import two_agents

    alphas = tuple(float(a) for a in
                   os.environ["CPR_SMOKE_ALPHAS"].split(","))
    tol = float(os.environ["CPR_SMOKE_TOL"])

    telemetry.current().manifest(dict(role="attack-smoke-anchor"))

    pols = ("honest", "sapirshtein-2016-sm1")
    env_rows = withholding_rows("nakamoto", policies=list(pols),
                                alphas=alphas, gammas=(0.0,),
                                episode_len=384, reps=48, seed=7)
    env_rel = {(r["attack"].removeprefix("nakamoto-"), r["alpha"]):
               r["relative_reward"] for r in env_rows}
    net_rows = attack_sweep(
        [("two-agents", two_agents(alpha=0.33,
                                   activation_delay=60.0))],
        policies=pols, alphas=alphas, activation_delays=(60.0,),
        activations=1200, reps=4, seed=7)
    assert not [r for r in net_rows if "error" in r], net_rows
    worst = 0.0
    for r in net_rows:
        p = r["attack"].removeprefix("nakamoto-")
        gap = abs(r["relative_reward"] - env_rel[(p, r["alpha"])])
        worst = max(worst, gap)
        assert gap < tol, (p, r["alpha"], r["relative_reward"],
                           env_rel[(p, r["alpha"])], tol)
    print(f"degenerate anchor: netsim attacker matches the two-party "
          f"env, worst gap {worst:.4f} < {tol}")
""")


def _sweep_run(work, devices):
    trace = os.path.join(work, f"sweep_d{devices}.jsonl")
    out_path = os.path.join(work, f"sweep_d{devices}.json")
    for p in (trace, out_path):
        if os.path.exists(p):
            os.remove(p)
    env = _child_env(work, trace, devices=devices, extra={
        "CPR_SMOKE_DEVICES": str(devices),
        "CPR_SMOKE_ALPHAS": ",".join(str(a) for a in ALPHAS),
        "CPR_SMOKE_POLICIES": ",".join(POLICIES),
        "CPR_SMOKE_ACTIVATIONS": str(ACTIVATIONS),
        "CPR_SMOKE_REPS": str(REPS),
        "CPR_SMOKE_OUT": out_path,
    })
    r = subprocess.run([sys.executable, "-c", _SWEEP_CHILD], env=env,
                       cwd=ROOT, capture_output=True, text=True,
                       timeout=WALL_S)
    sys.stderr.write(r.stdout)
    if r.returncode != 0:
        sys.stderr.write(r.stderr)
        raise SystemExit(f"sweep child (devices={devices}) failed "
                         f"rc={r.returncode}")
    _validate_stream(trace, "attack_sweep")
    with open(out_path) as f:
        rows = json.load(f)
    _log(f"sweep child devices={devices}: {len(rows)} rows")
    return rows, trace


def _anchor_run(work):
    trace = os.path.join(work, "anchor.jsonl")
    if os.path.exists(trace):
        os.remove(trace)
    env = _child_env(work, trace, extra={
        "CPR_SMOKE_ALPHAS": ",".join(str(a) for a in ALPHAS),
        "CPR_SMOKE_TOL": str(TOLERANCE),
    })
    r = subprocess.run([sys.executable, "-c", _ANCHOR_CHILD], env=env,
                       cwd=ROOT, capture_output=True, text=True,
                       timeout=WALL_S)
    sys.stderr.write(r.stdout)
    if r.returncode != 0:
        sys.stderr.write(r.stderr)
        raise SystemExit(f"anchor child failed rc={r.returncode}")
    _validate_stream(trace, "attack_sweep")
    _log("degenerate two-party anchor held")


def _wait_ready(path, proc):
    deadline = time.time() + READY_TIMEOUT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            raise SystemExit(f"server child exited rc={proc.returncode} "
                             f"before becoming ready")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            time.sleep(0.25)
    raise SystemExit(f"server not ready within {READY_TIMEOUT_S:.0f}s")


def _serve_run(work):
    """Supervised serve child answering netsim.attack_sweep: the repeat
    query must hit the topology-fingerprint sweep cache."""
    trace = os.path.join(work, "serve_attack.jsonl")
    if os.path.exists(trace):
        os.remove(trace)
    cmd = [sys.executable, "-m", "cpr_tpu.serve.server",
           "--protocol", "nakamoto", "--max-steps", "64",
           "--lanes", "2", "--burst", "32", "--devices", "1",
           "--heartbeat-s", "0.5",
           "--ready-file", os.path.join(work, "ready_attack.json")]
    started = threading.Event()
    box = {}

    def on_start(proc):
        box["proc"] = proc
        started.set()

    def supervise():
        box["attempt"] = supervisor.run_child(
            cmd, wall_timeout_s=WALL_S, quiet_s=60.0, heartbeat_s=1.0,
            env=_child_env(work, trace), cwd=ROOT, on_start=on_start)

    child = threading.Thread(target=supervise)
    child.start()
    try:
        if not started.wait(30.0):
            raise SystemExit("run_child never spawned the server")
        ready = _wait_ready(os.path.join(work, "ready_attack.json"),
                            box["proc"])
        port = ready["port"]
        _log(f"serve child ready on port {port}")
        query = dict(topology={"kind": "two-agents"},
                     policies=list(POLICIES), alphas=list(ALPHAS),
                     activations=400, reps=2, seed=3)
        with ServeClient("127.0.0.1", port) as c:
            r1 = c.request("netsim.attack_sweep", **query)
            assert r1.get("ok"), f"netsim.attack_sweep: {r1}"
            assert r1["cached"] is False, r1
            assert not [r for r in r1["rows"] if "error" in r], r1
            r2 = c.request("netsim.attack_sweep", **query)
            assert r2.get("ok") and r2["cached"] is True, r2
        if r1["rows"] != r2["rows"]:
            raise SystemExit("cached netsim.attack_sweep replay changed "
                             "the row table")
        if r1["topo_fingerprint"] != r2["topo_fingerprint"]:
            raise SystemExit("sweep-cache topology fingerprint drifted "
                             "between identical queries")
        box["proc"].send_signal(signal.SIGTERM)
    except BaseException:
        proc = box.get("proc")
        if proc is not None and proc.poll() is None:
            proc.kill()
        raise
    child.join(120.0)
    if child.is_alive():
        raise SystemExit("server child did not drain within 120s")
    attempt = box["attempt"]
    if attempt.status != "ok" or attempt.rc != 0:
        raise SystemExit(f"serve child did not exit cleanly "
                         f"(status={attempt.status} rc={attempt.rc})")
    _validate_stream(trace, "serve,attack_sweep")
    _log(f"serve netsim.attack_sweep: swept then cache-hit, "
         f"{len(r1['rows'])} rows, drained clean")
    return trace


def _bank_and_gate(work, traces):
    """The same-shaped sweep traces into one ledger;
    attack_sweep_lanes_per_sec must land at both device counts with
    its protocol/topology config attached, and every banked row must
    clear the gate."""
    ledger = Ledger(os.path.join(work, "perf_ledger.jsonl"))
    n = sum(ledger.ingest_trace(t) for t in traces)
    records = ledger.records()
    lps = [r for r in records
           if r.get("metric") == "attack_sweep_lanes_per_sec"]
    if not lps:
        raise SystemExit("no attack_sweep_lanes_per_sec rows banked")
    got = {r.get("config", {}).get("cfg_devices") for r in lps}
    if not {1, DEVICES} <= got:
        raise SystemExit(f"attack_sweep_lanes_per_sec banked at device "
                         f"counts {sorted(got)}, need both 1 and "
                         f"{DEVICES}")
    for r in lps:
        cfg = r.get("config", {})
        if not cfg.get("cfg_protocol") or not cfg.get("cfg_topology"):
            raise SystemExit(f"attack_sweep row missing "
                             f"cfg_protocol/cfg_topology: {r}")
    results = [gate_row(r, records) for r in records]
    summary = gate_summary(results)
    if not summary["ok"]:
        bad = [res for res in results if res["verdict"] == "fail"]
        raise SystemExit(f"attack perf gate failed: {bad}")
    return n, summary


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-attack-smoke"
    os.makedirs(work, exist_ok=True)

    rows_1, trace_1 = _sweep_run(work, 1)
    rows_n, trace_n = _sweep_run(work, DEVICES)
    if rows_1 != rows_n:
        raise SystemExit(f"attack sweep rows NOT bit-identical between "
                         f"1-device and {DEVICES}-device runs")
    _log(f"sweep rows bit-identical at 1 vs {DEVICES} devices "
         f"({len(rows_1)} rows)")

    _anchor_run(work)
    _serve_run(work)

    n, summary = _bank_and_gate(work, [trace_1, trace_n])
    print(f"attack-smoke: PASS (clique-4 attack sweep bit-identical at "
          f"1 vs {DEVICES} devices over {len(rows_1)} rows; degenerate "
          f"two-party anchor within {TOLERANCE}; serve "
          f"netsim.attack_sweep cache-hit round-trip with clean "
          f"SIGTERM drain; banked {n} ledger rows incl. "
          f"attack_sweep_lanes_per_sec at devices 1 and {DEVICES}; "
          f"gate {summary})")


if __name__ == "__main__":
    main()
