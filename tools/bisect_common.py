"""Shared harness for the on-chip fault-bisection tools.

Each candidate snippet runs in its own watchdog-bounded subprocess via
`cpr_tpu/supervisor.run_child` (wall-clock only: candidates are raw
`-c` snippets with no heartbeat): a crashed worker can wedge backend
init for the NEXT process, so the parent classifies crash-rc,
crash-signature stderr, and init-hang separately.  `run_candidates`
additionally probes the device before the first candidate and stops at
the first CRASH/HANG to avoid hammering a wedged chip.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cpr_tpu import supervisor  # noqa: E402

PRE = "import jax, jax.numpy as jnp\n"

# stderr substrings that mean the device itself crashed (vs a python rc)
CRASH_SIGNATURES = ("crashed or restarted", "UNAVAILABLE")


def run_one(name, code, timeout=300.0):
    a = supervisor.run_child(
        [sys.executable, "-u", "-c", PRE + code], cwd=REPO,
        wall_timeout_s=timeout, quiet_s=None, forward_stderr=False)
    if a.status in ("hung", "stalled"):
        return name, "HANG", a.dur_s, ""
    status = "ok" if a.status == "ok" else f"rc={a.rc}"
    err = a.stderr_tail
    tail = (err.strip().splitlines() or [""])[-1]
    if any(sig in err for sig in CRASH_SIGNATURES):
        status = "CRASH"
    return (name, status, a.dur_s,
            tail if status != "ok" else a.stdout.strip())


def run_candidates(candidates, limit=None, timeout=300.0):
    """Run candidates in order, printing one status line each; stop at
    the first CRASH/HANG (wedged-chip discipline).  A bounded device
    probe runs first so a chip wedged by an earlier session costs
    seconds, not the first candidate's full timeout."""
    pr = supervisor.probe()
    print(f"probe: {pr['reason']} [{pr.get('backend')}] "
          f"{pr['dur_s']:.1f}s", flush=True)
    if not pr["ok"]:
        print("stopping: device probe failed; wait before re-running",
              flush=True)
        return
    for name, code in candidates[:limit]:
        name, status, dt, info = run_one(name, code, timeout=timeout)
        print(f"{name:24s} {status:8s} {dt:6.1f}s  {info[:100]}", flush=True)
        if status in ("CRASH", "HANG"):
            print("stopping: chip likely wedged; wait before re-running",
                  flush=True)
            break
