"""Shared harness for the on-chip fault-bisection tools.

Each candidate snippet runs in its own watchdog-bounded subprocess (the
bench.py pattern): a crashed worker can wedge backend init for the NEXT
process, so the parent classifies crash-rc, crash-signature stderr, and
init-hang separately and stops at the first CRASH/HANG to avoid
hammering a wedged chip.
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PRE = "import jax, jax.numpy as jnp\n"

# stderr substrings that mean the device itself crashed (vs a python rc)
CRASH_SIGNATURES = ("crashed or restarted", "UNAVAILABLE")


def run_one(name, code, timeout=300.0):
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", PRE + code], cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    t0 = time.time()
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            pass
        return name, "HANG", time.time() - t0, ""
    status = "ok" if proc.returncode == 0 else f"rc={proc.returncode}"
    tail = (err.strip().splitlines() or [""])[-1]
    if any(sig in err for sig in CRASH_SIGNATURES):
        status = "CRASH"
    return name, status, time.time() - t0, tail if status != "ok" else out.strip()


def run_candidates(candidates, limit=None, timeout=300.0):
    """Run candidates in order, printing one status line each; stop at
    the first CRASH/HANG (wedged-chip discipline)."""
    for name, code in candidates[:limit]:
        name, status, dt, info = run_one(name, code, timeout=timeout)
        print(f"{name:24s} {status:8s} {dt:6.1f}s  {info[:100]}", flush=True)
        if status in ("CRASH", "HANG"):
            print("stopping: chip likely wedged; wait before re-running",
                  flush=True)
            break
