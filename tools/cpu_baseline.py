"""Bank CPU baselines for the BASELINE target configs (VERDICT r3 #1).

The reference publishes no numbers (BASELINE.md), so the defensible
stand-in for "the reference engine on CPU" is this repo's own C++
discrete-event oracle — the same simulation semantics as the reference's
OCaml engine (protocol agents, per-node views, flooding), compiled
native, driven by activations.  One activation == one env step in the
SSZ attack spaces (each step assigns one PoW puzzle solution), so
oracle activations/sec is directly comparable to the gym envs'
env-steps/sec (reference metric shape:
gym/ocaml/test/test_benchmark.py:13-23 measures episode wall-time for
the same loop).

Two rates per config:
  - single_core: one OracleSim on one core — the reference's execution
    model (one sim task = one process; csv_runner.ml parallelizes only
    across tasks).
  - socket: cpu_count() independent sims in parallel processes — the
    fairest "whole host vs one chip" comparison.

Writes BASELINE_CPU.json next to the repo root; bench.py reads it to
stamp a vs_cpu_baseline field into every BENCH_CONFIGS row.

Usage: python tools/cpu_baseline.py [--quick]
"""

import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# (protocol, k, scheme, attacker_policy) per BASELINE config; alpha/gamma
# match the bench configs (0.35 / 0.5, selfish_mining topology)
ORACLE_CONFIGS = {
    "nakamoto_sm1": ("nakamoto", 0, "", "sapirshtein-2016-sm1"),
    "bk8_withholding": ("bk", 8, "constant", "get-ahead"),
    "ethereum_uncle_attack": ("ethereum-byzantium", 0, "", "fn19"),
    "tailstorm_ppo_train": ("tailstorm", 8, "", "get-ahead"),
}


def _rate_one(args):
    (protocol, k, scheme, policy), n, seed = args
    from cpr_tpu.native import OracleSim

    s = OracleSim(protocol=protocol, k=k, scheme=scheme,
                  topology="selfish_mining", alpha=0.35, gamma=0.5,
                  attacker_policy=policy, seed=seed)
    s.run(max(n // 20, 1000))  # warm caches / allocator
    t0 = time.time()
    s.run(n)
    dt = time.time() - t0
    s.close()
    return n / dt


def measure(name, n=200_000, workers=None):
    spec = ORACLE_CONFIGS[name]
    single = _rate_one((spec, n, 1))
    workers = workers or (os.cpu_count() or 1)
    row = {"single_core_steps_per_sec": round(single),
           "socket_workers": workers}
    if workers == 1:
        # single-core host: the socket rate IS the single-core rate; a
        # 1-worker pool would only measure spawn/import overhead
        row["socket_steps_per_sec_sum"] = round(single)
        return row
    with mp.get_context("spawn").Pool(workers) as pool:
        rates = pool.map(_rate_one,
                         [(spec, n, 100 + i) for i in range(workers)])
    # sum of independent per-worker warm rates (excludes pool startup;
    # the honest steady-state aggregate for long sweeps)
    row["socket_steps_per_sec_sum"] = round(sum(rates))
    return row


def main():
    quick = "--quick" in sys.argv
    n = 50_000 if quick else 200_000
    out = {
        "hardware": f"{os.cpu_count()}-core host CPU (single socket)",
        "engine": "cpr_tpu C++ oracle (native/src/oracle.cpp), -O2",
        "topology": "selfish_mining alpha=0.35 gamma=0.5",
        "metric": "activations/sec == env-steps/sec (SSZ attack space)",
        "configs": {},
    }
    for name in ORACLE_CONFIGS:
        row = measure(name, n=n)
        out["configs"][name] = row
        print(json.dumps({"config": name, **row}), flush=True)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "BASELINE_CPU.json")
    # lazy import: measure() already pulls cpr_tpu for the oracle, so
    # the atomic helper costs nothing extra by the time we bank results
    from cpr_tpu.resilience import atomic_write_json

    atomic_write_json(os.path.abspath(path), out)
    print(f"wrote {os.path.abspath(path)}", file=sys.stderr)


if __name__ == "__main__":
    main()
