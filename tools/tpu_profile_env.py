"""Device-profile one DAG-family bench config and print the top ops.

Captures a `jax.profiler.trace` of warm bench-shape reps (the axon
worker returns real per-op device timelines — docs/TPU_SESSION_r04.md),
parses the chrome-trace json.gz, and aggregates device-lane op time by
HLO op name so a perf round starts from evidence, not guesses.

Usage: python tools/tpu_profile_env.py <bk|ethereum|tailstorm> [n_envs]
           [top_n]
"""

import glob
import gzip
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from cpr_tpu import telemetry  # noqa: E402
from cpr_tpu.telemetry import now  # noqa: E402


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def build(config, n_envs):
    """Return (fn, keys, n_steps) — one warmable chunked call per rep,
    matching bench.py's shapes."""
    import jax

    from cpr_tpu.params import make_params

    if config == "bk":
        from cpr_tpu.envs.bk import BkSSZ
        env = BkSSZ(k=8, incentive_scheme="constant", max_steps_hint=128)
        params = make_params(alpha=0.35, gamma=0.5, max_steps=120)
        fn = env.make_episode_stats_fn(params, env.policies["get-ahead"],
                                       128, chunk=128)
        n_steps = 128
    elif config == "ethereum":
        from cpr_tpu.envs.ethereum import EthereumSSZ
        env = EthereumSSZ("byzantium", max_steps_hint=128)
        params = make_params(alpha=0.35, gamma=0.5, max_steps=120)
        fn = env.make_episode_stats_fn(params, env.policies["fn19"],
                                       128, chunk=128)
        n_steps = 128
    elif config == "tailstorm":
        from cpr_tpu.envs.registry import get_sized
        from cpr_tpu.train.ppo import PPOConfig, make_train
        env = get_sized("tailstorm-8-discount-heuristic", 128)
        params = make_params(alpha=0.35, gamma=0.5, max_steps=120)
        cfg = PPOConfig(n_envs=n_envs, n_steps=128)
        init_fn, train_step = make_train(env, params, cfg)
        # one-shot init: constructed and called exactly once
        # jaxlint: disable-next-line=jit-in-loop
        carry = jax.jit(init_fn)(jax.random.PRNGKey(0))
        step = jax.jit(train_step)
        state = {"carry": carry}

        def fn(_keys):
            state["carry"], m = step(state["carry"])
            return m

        return fn, None, 128
    else:
        raise SystemExit(f"unknown config {config}")
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    return fn, keys, n_steps


def fetch(out):
    import numpy as np

    leaves = [v for v in (out.values() if isinstance(out, dict) else [out])]
    return float(np.asarray(leaves[0]).reshape(-1)[0])


def summarize(trace_dir, top_n):
    """Aggregate device-lane events from the newest trace.json.gz."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        log(f"no trace under {trace_dir}")
        return
    with gzip.open(paths[-1], "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # device lanes: pid names containing "TPU"/"Device"; host lanes are
    # python/runtime noise
    dev_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            name = e.get("args", {}).get("name", "")
            if "TPU" in name or "Device" in name or "/device:" in name:
                dev_pids.add(e.get("pid"))
    agg = {}
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        dur = float(e.get("dur", 0.0))
        name = e.get("name", "?")
        total += dur
        a = agg.setdefault(name, [0.0, 0])
        a[0] += dur
        a[1] += 1
    rows = sorted(agg.items(), key=lambda kv: -kv[1][0])[:top_n]
    print(f"device total {total / 1e3:.1f} ms across {len(agg)} op names")
    for name, (dur, cnt) in rows:
        print(f"{dur / 1e3:9.2f} ms {cnt:6d}x  {100 * dur / total:5.1f}%  "
              f"{name[:110]}")


def main():
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    config = sys.argv[1]
    n_envs = int(sys.argv[2]) if len(sys.argv) > 2 else (
        8192 if config == "bk" else 4096)
    top_n = int(sys.argv[3]) if len(sys.argv) > 3 else 40

    fn, keys, n_steps = build(config, n_envs)
    tele = telemetry.current()
    log(f"compiling {config} n_envs={n_envs}")
    t0 = now()
    fetch(fn(keys) if keys is not None else fn(None))
    log(f"compile+first {now() - t0:.1f}s; warm rep...")
    with tele.span("profile_warm_rep",
                   env_steps=n_envs * n_steps) as sp:
        sp.fence(fn(keys) if keys is not None else fn(None))
    dt = sp.dur_s
    log(f"warm rep {dt:.2f}s = {n_envs * n_steps / dt:,.0f} steps/s")

    # CPR_PROFILE_DIR (the telemetry-wide knob) wins over the legacy
    # CPR_TRACE_DIR this tool grew first
    trace_dir = (os.environ.get(telemetry.PROFILE_ENV_VAR)
                 or os.environ.get("CPR_TRACE_DIR")
                 or tempfile.mkdtemp(prefix=f"trace_{config}_"))
    log(f"tracing into {trace_dir}")
    with telemetry.profile_trace(trace_dir):
        fetch(fn(keys) if keys is not None else fn(None))
    summarize(trace_dir, top_n)


if __name__ == "__main__":
    main()
