"""State-sharded VI smoke (`make vi-smoke`).

Proves the state-sharded exact-analysis seam (docs/MDP.md
"State-sharded solving") end-to-end on the CPU CI host — solve
children run under forced 1-device and 4-device XLA CPU meshes:

  1  per device count, a solve child parametrically compiles
     bitcoin (fc16) at fork-length 6, revalues one (alpha, gamma)
     point, and solves it through
     `parallel.sharded_state_value_iteration` (4 devices shard the
     89-state space with `pad_states`, 1 device runs the degenerate
     single-shard program);
  2  device-count parity: value/progress/policy fixpoints and the
     convergence sweep must be BIT-IDENTICAL between the 1- and
     4-device runs, and the 1-device child additionally pins them
     bit-identical to the solo `value_iteration(impl="chunked")`
     oracle — sharding is an execution strategy, not an
     approximation;
  3  the 1-device child runs the in-graph RTDP
     (`mdp.rtdp_graph.rtdp_graph`, one `lax.while_loop`, seeded)
     and checks its start value against the host-computed exact-VI
     oracle; the 4-device child runs the full
     `rtdp_sharded_polish` handoff (explore in-graph, certify with
     the sharded VI) and checks the polished fixpoint against its
     own sharded solve;
  4  the 4-device child also solves a 2x2 (alpha, gamma) grid of
     aft20 on the composed ("g", "s") 2-D mesh and pins it
     bit-identical to the 1-D grid solve (grid x state
     composition);
  5  every trace passes `trace_summary --validate --expect
     mdp_solve`, and all traces ingest into one perf ledger:
     `mdp_states_per_sec` rows must land at BOTH state-shard counts
     (cfg_state_shards absent == 1, and 4), the composed grid solve
     must bank `mdp_grid_points_per_sec`, and every banked row must
     clear the regression gate.

Usage: python tools/vi_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import subprocess
import sys
import textwrap

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from cpr_tpu.perf.gate import gate_row, gate_summary  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402

DEVICES = 4
MFL = 6                      # bitcoin (fc16) fork-length
HORIZON = 20
ALPHA, GAMMA = 0.35, 0.5
WALL_S = 900.0


def _log(msg):
    print(f"vi-smoke: {msg}", file=sys.stderr)


def _child_env(workdir, trace, extra=None, devices=1):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count="
                         f"{devices}",
               CPR_TELEMETRY=trace,
               CPR_TPU_CACHE=os.path.join(workdir, "cache"))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def _validate_stream(trace, expect):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, trace, "--validate", "--expect", expect],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


# one solve child per device count: the same bitcoin@6 point through
# the sharded VI, exact outputs dumped as JSON for the parent's
# cross-device bit-identity check.  The 1-device child adds the solo
# oracle + in-graph-RTDP value check; the 4-device child adds the
# polish handoff and the composed grid x state solve.
_SOLVE_CHILD = textwrap.dedent("""\
    import json, os

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from cpr_tpu import telemetry
    from cpr_tpu.mdp.explicit import MDP
    from cpr_tpu.mdp.grid import (compile_protocol, grid_value_iteration,
                                  param_ptmdp)
    from cpr_tpu.mdp.rtdp_graph import rtdp_graph, rtdp_sharded_polish
    from cpr_tpu.parallel import (default_mesh,
                                  sharded_state_value_iteration)

    devices = int(os.environ["CPR_SMOKE_DEVICES"])
    mfl = int(os.environ["CPR_SMOKE_MFL"])
    horizon = int(os.environ["CPR_SMOKE_HORIZON"])
    alpha = float(os.environ["CPR_SMOKE_ALPHA"])
    gamma = float(os.environ["CPR_SMOKE_GAMMA"])

    devs = jax.devices()
    assert len(devs) >= devices, (len(devs), devices)
    mesh = default_mesh(devices=devs[:devices])

    tele = telemetry.current()
    tele.manifest(dict(role="vi-smoke-solve", devices=devices,
                       mfl=mfl, horizon=horizon))

    def point_tensor(pm, a, g):
        m = pm.mdp
        sv = pm._monomial(pm.start_coef, pm.start_expo, a, g)
        return MDP(n_states=m.n_states, n_actions=m.n_actions,
                   start={int(s): float(v)
                          for s, v in zip(pm.start_ids, sv)},
                   src=m.src, act=m.act, dst=m.dst,
                   prob=pm.revalue(a, g),
                   reward=m.reward, progress=m.progress).tensor()

    pm = param_ptmdp(compile_protocol("fc16", cutoff=mfl),
                     horizon=horizon)
    tm = point_tensor(pm, alpha, gamma)
    vi = sharded_state_value_iteration(
        tm, mesh, stop_delta=1e-6, pad_states=True,
        protocol="fc16", cutoff=mfl)
    assert vi["vi_state_shards"] == devices
    payload = dict(devices=devices, vi=dict(
        value=vi["vi_value"].tolist(),
        progress=vi["vi_progress"].tolist(),
        policy=vi["vi_policy"].tolist(),
        sweeps=int(vi["vi_iter"])))
    print(f"sharded solve: {tm.n_states} states over {devices} "
          f"shard(s), {vi['vi_iter']} sweeps")

    if devices == 1:
        # solo oracle: the sharded program at one shard must BE the
        # solo chunked solve, bit for bit
        solo = tm.value_iteration(impl="chunked", stop_delta=1e-6)
        for k in ("vi_value", "vi_progress", "vi_policy"):
            assert np.array_equal(vi[k], solo[k]), k
        assert int(vi["vi_iter"]) == int(solo["vi_iter"])
        print("1-shard fixpoint bit-identical to solo chunked VI")
        # in-graph RTDP vs the host-computed exact oracle
        sv_exact = tm.start_value(solo["vi_value"])
        r = rtdp_graph(tm, jax.random.PRNGKey(0), max_steps=4000,
                       batch=128, buffer=256)
        sv_rtdp = tm.start_value(r["rtdp_value"])
        assert abs(sv_rtdp - sv_exact) <= 1e-3 * max(
            1.0, abs(sv_exact)), (sv_rtdp, sv_exact)
        # seeded: a re-run is bit-identical
        r2 = rtdp_graph(tm, jax.random.PRNGKey(0), max_steps=4000,
                        batch=128, buffer=256)
        assert np.array_equal(r["rtdp_value"], r2["rtdp_value"])
        print(f"in-graph RTDP start value {sv_rtdp:.6f} matches "
              f"exact oracle {sv_exact:.6f} (seeded, reproducible)")
    else:
        # oracle solves (the warm-started polish, the mesh=None grid
        # reference) are correctness checks, not measurements: their
        # mdp_solve events go to a separate validated-but-unbanked
        # trace so their rates (compile time amortized over fewer or
        # differently-batched sweeps) never gate the cold rows
        telemetry.configure(os.environ["CPR_SMOKE_ORACLE"])
        telemetry.current().manifest(dict(role="vi-smoke-oracle",
                                          devices=devices))
        # explore in-graph, certify with the sharded VI
        pol = rtdp_sharded_polish(
            tm, mesh, jax.random.PRNGKey(0), rtdp_steps=2000,
            batch=128, stop_delta=1e-6, pad_states=True,
            protocol="fc16", cutoff=mfl)
        assert pol["vi_state_shards"] == devices
        assert pol["vi_iter"] <= int(vi["vi_iter"])
        assert np.allclose(pol["vi_value"], vi["vi_value"], atol=1e-5)
        print(f"rtdp_sharded_polish: {pol['rtdp_steps']} RTDP steps "
              f"then {pol['vi_iter']} sweeps (cold: {vi['vi_iter']})")
        # composed grid x state 2-D mesh vs the 1-D grid solve
        pt2 = param_ptmdp(compile_protocol("aft20", cutoff=mfl),
                          horizon=horizon)
        alphas, gammas = (0.3, 0.4), (0.25, 0.75)
        ref = grid_value_iteration(pt2, alphas, gammas,
                                   stop_delta=1e-6, mesh=None,
                                   protocol="aft20", cutoff=mfl)
        telemetry.configure(os.environ["CPR_TELEMETRY"])  # appends
        mesh2 = jax.sharding.Mesh(
            np.asarray(devs[:devices]).reshape(2, devices // 2),
            ("g", "s"))
        got = grid_value_iteration(pt2, alphas, gammas,
                                   stop_delta=1e-6, mesh=mesh2,
                                   axis="g", state_axis="s",
                                   protocol="aft20", cutoff=mfl)
        for k in ("grid_value", "grid_progress", "grid_policy"):
            assert np.array_equal(np.asarray(ref[k]),
                                  np.asarray(got[k])), k
        assert int(ref["vi_iter"]) == int(got["vi_iter"])
        print(f"composed ('g', 's') grid solve bit-identical to the "
              f"1-D grid solve ({got['vi_iter']} sweeps)")

    with open(os.environ["CPR_SMOKE_OUT"], "w") as f:
        json.dump(payload, f, sort_keys=True)
    print("vi solve child ok:", devices, "device(s)")
""")


def _solve_run(work, devices):
    trace = os.path.join(work, f"solve_d{devices}.jsonl")
    oracle = os.path.join(work, f"oracle_d{devices}.jsonl")
    out_path = os.path.join(work, f"solve_d{devices}.json")
    for p in (trace, oracle, out_path):
        if os.path.exists(p):
            os.remove(p)
    env = _child_env(work, trace, devices=devices, extra={
        "CPR_SMOKE_DEVICES": str(devices),
        "CPR_SMOKE_MFL": str(MFL),
        "CPR_SMOKE_HORIZON": str(HORIZON),
        "CPR_SMOKE_ALPHA": str(ALPHA),
        "CPR_SMOKE_GAMMA": str(GAMMA),
        "CPR_SMOKE_ORACLE": oracle,
        "CPR_SMOKE_OUT": out_path,
    })
    r = subprocess.run([sys.executable, "-c", _SOLVE_CHILD], env=env,
                       cwd=ROOT, capture_output=True, text=True,
                       timeout=WALL_S)
    sys.stderr.write(r.stdout)
    if r.returncode != 0:
        sys.stderr.write(r.stderr)
        raise SystemExit(f"solve child (devices={devices}) failed "
                         f"rc={r.returncode}")
    _validate_stream(trace, "mdp_solve")
    if os.path.exists(oracle):
        _validate_stream(oracle, "mdp_solve")
    with open(out_path) as f:
        payload = json.load(f)
    _log(f"solve child devices={devices} ok")
    return payload, trace


def _bank_and_gate(work, traces):
    """All traces into one ledger; mdp_states_per_sec must land at
    both state-shard counts, the composed grid solve must bank
    mdp_grid_points_per_sec, and every row must clear the gate."""
    ledger = Ledger(os.path.join(work, "perf_ledger.jsonl"))
    n = sum(ledger.ingest_trace(t) for t in traces)
    records = ledger.records()
    sps = [r for r in records
           if r.get("metric") == "mdp_states_per_sec"]
    got = {r.get("config", {}).get("cfg_state_shards", 1) for r in sps}
    if not {1, DEVICES} <= got:
        raise SystemExit(f"mdp_states_per_sec banked at state-shard "
                         f"counts {sorted(got)}, need both 1 and "
                         f"{DEVICES}")
    if not any(r.get("metric") == "mdp_grid_points_per_sec"
               for r in records):
        raise SystemExit("composed grid solve banked no "
                         "mdp_grid_points_per_sec row")
    results = [gate_row(r, records) for r in records]
    summary = gate_summary(results)
    if not summary["ok"]:
        bad = [res for res in results if res["verdict"] == "fail"]
        raise SystemExit(f"vi perf gate failed: {bad}")
    return n, summary


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-vi-smoke"
    os.makedirs(work, exist_ok=True)

    out_1, trace_1 = _solve_run(work, 1)
    out_n, trace_n = _solve_run(work, DEVICES)
    if out_1["vi"] != out_n["vi"]:
        raise SystemExit(f"state-sharded solves NOT bit-identical "
                         f"between 1-device and {DEVICES}-device runs")
    _log(f"sharded fixpoints bit-identical at 1 vs {DEVICES} shards "
         f"(bitcoin fc16@{MFL}, {out_1['vi']['sweeps']} sweeps)")

    n, summary = _bank_and_gate(work, [trace_1, trace_n])
    print(f"vi-smoke: PASS (state-sharded VI bit-identical at 1 vs "
          f"{DEVICES} forced CPU devices on bitcoin fc16@{MFL}; solo-"
          f"oracle and in-graph-RTDP value checks; rtdp_sharded_polish "
          f"handoff; composed ('g', 's') 2-D grid solve bit-identical; "
          f"banked {n} ledger rows incl. mdp_states_per_sec at shard "
          f"counts 1 and {DEVICES}; gate {summary})")


if __name__ == "__main__":
    main()
