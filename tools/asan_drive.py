"""Drive the ASan builds of both native libraries (see `make asan`).

Exercises every protocol module, topology, and withholding-agent family
in the oracle, and every protocol spec + flag path in the generic-MDP
compiler — the C++ surface a memory bug could hide in.  Run under
LD_PRELOAD=libasan.so; any ASan report aborts with a nonzero exit.
"""

import ctypes


def drive_compiler(path="/tmp/libgc_asan.so"):
    L = ctypes.CDLL(path)
    L.gmc_compile.restype = ctypes.c_void_p
    L.gmc_compile.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_double, ctypes.c_double,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int64]
    for f in ("gmc_n_states", "gmc_n_transitions"):
        getattr(L, f).restype = ctypes.c_int64
        getattr(L, f).argtypes = [ctypes.c_void_p]
    L.gmc_error.restype = ctypes.c_char_p
    L.gmc_error.argtypes = [ctypes.c_void_p]
    L.gmc_free.argtypes = [ctypes.c_void_p]

    cases = [(b"ghostdag", 2), (b"bitcoin", 0), (b"parallel", 2),
             (b"ethereum", 3), (b"byzantium", 3)]
    for proto, k in cases:
        # (proto, k, alpha, gamma, dag_cutoff, height_cutoff, gc_mode,
        #  merge_iso, truncate, loop_honest, reward_cc, force_own, cap)
        h = L.gmc_compile(proto, k, 0.33, 0.5, 6, -1, 1, 1, 1, 0, 0, 0,
                          10**7)
        # a non-null handle can still carry a partial-compile error
        # (state cap, probability-sum failure)
        assert h and not L.gmc_error(h), (proto, L.gmc_error(h))
        print("compiler", proto.decode(), int(L.gmc_n_states(h)),
              int(L.gmc_n_transitions(h)), flush=True)
        L.gmc_free(h)
    # flag variants on bitcoin (judge GC, loop-honest, reward-cc)
    for args in ((5, -1, 2, 1, 1, 0, 0, 0), (5, -1, 1, 1, 0, 1, 0, 0),
                 (5, -1, 1, 1, 1, 0, 1, 0), (5, -1, 1, 1, 1, 0, 0, 1)):
        h = L.gmc_compile(b"bitcoin", 0, 0.3, 0.5, *args, 10**6)
        assert h and not L.gmc_error(h), (args, L.gmc_error(h))
        L.gmc_free(h)
    print("compiler flag variants: clean", flush=True)


def drive_oracle(path="/tmp/liborc_asan.so"):
    L = ctypes.CDLL(path)
    L.cpr_oracle_create.restype = ctypes.c_void_p
    L.cpr_oracle_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_double, ctypes.c_double, ctypes.c_int,
        ctypes.c_double, ctypes.c_double, ctypes.c_char_p,
        ctypes.c_uint64]
    L.cpr_oracle_run.restype = ctypes.c_long
    L.cpr_oracle_run.argtypes = [ctypes.c_void_p, ctypes.c_long]
    L.cpr_oracle_metric.restype = ctypes.c_double
    L.cpr_oracle_metric.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_int]
    L.cpr_oracle_destroy.argtypes = [ctypes.c_void_p]

    cases = [
        (b"nakamoto", 0, b"", b"selfish_mining", b"sapirshtein-2016-sm1"),
        (b"nakamoto", 0, b"", b"selfish_mining", b"eyal-sirer-2014"),
        (b"ethereum-byzantium", 0, b"", b"selfish_mining", b"fn19"),
        (b"ethereum-whitepaper", 0, b"", b"selfish_mining", b"fn19pkel"),
        (b"bk", 4, b"constant", b"selfish_mining", b"get-ahead"),
        (b"bk", 8, b"block", b"clique", b"none"),
        (b"tailstorm", 4, b"discount", b"two_agents", b"none"),
        (b"stree", 4, b"discount", b"clique", b"none"),
        (b"sdag", 4, b"constant", b"two_agents", b"none"),
        (b"spar", 4, b"constant", b"clique", b"none"),
        # parallel-family withholding agent (ParAgent): generic release
        # scan + dedup/unlock interplay under every policy branch
        (b"spar", 4, b"constant", b"selfish_mining", b"selfish"),
        (b"stree", 4, b"discount", b"selfish_mining", b"minor-delay"),
        (b"sdag", 4, b"constant", b"selfish_mining", b"minor-delay"),
        (b"tailstorm", 4, b"discount", b"selfish_mining", b"minor-delay"),
        (b"tailstorm", 4, b"constant", b"selfish_mining", b"get-ahead"),
        (b"tailstorm", 4, b"constant", b"selfish_mining", b"honest"),
        (b"stree", 4, b"constant", b"selfish_mining", b"avoid-loss"),
        (b"tailstorm", 4, b"discount", b"selfish_mining", b"avoid-loss"),
    ]
    for proto, k, sch, topo, pol in cases:
        h = L.cpr_oracle_create(proto, k, sch, topo, 7, 0.35, 0.5, 2,
                                1.0, 1e-9, pol, 3)
        assert h, (proto, topo, pol)
        L.cpr_oracle_run(h, 20_000)
        print("oracle", proto.decode(), topo.decode(), pol.decode(),
              round(L.cpr_oracle_metric(h, 0, 0), 1), flush=True)
        L.cpr_oracle_destroy(h)
    print("oracle: clean", flush=True)


if __name__ == "__main__":
    drive_compiler()
    drive_oracle()
    print("ASAN drive: all clean")
