"""Bisect the axon-TPU ethereum-env kernel fault, one candidate per child.

The round-3 chip session showed `EthereumSSZ.episode_stats` faulting the
TPU device at EVERY batch size (65536/16384/4096 envs) while the bk and
tailstorm DAG-tensor envs ran fine — so the fault is a construct the
ethereum env uses and they don't, not memory pressure.  Candidates walk
up the ethereum step: reset, chain_window (the unrolled uncle-window
ancestor walk), uncle selection, a single step, then scans of growing
size, with a bk scan as the known-good control.

Same harness discipline as tools/tpu_vi_bisect.py: each candidate runs
in a watchdog-bounded subprocess; stop at the first CRASH/HANG so a
wedged chip isn't hammered.

Usage: python tools/tpu_eth_bisect.py [max_candidates]
"""

import sys

# run as a script from anywhere: the tools dir is sys.path[0] only for
# direct execution, so resolve it explicitly
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from bisect_common import run_candidates  # noqa: E402

ENV = """
from cpr_tpu.envs.ethereum import EthereumSSZ
from cpr_tpu.params import make_params
env = EthereumSSZ("byzantium", max_steps_hint=64)
params = make_params(alpha=0.35, gamma=0.5, max_steps=56)
key = jax.random.PRNGKey(0)
"""

CANDIDATES = [
    ("baseline_sum", "print(int(jnp.arange(8).sum()))"),
    ("eth_reset", ENV + """
state, obs = jax.jit(env.reset)(key, params)
print(float(jnp.asarray(obs).sum()))"""),
    ("eth_reset_vmap", ENV + """
keys = jax.random.split(key, 256)
state, obs = jax.jit(jax.vmap(lambda k: env.reset(k, params)))(keys)
print(float(jnp.asarray(obs).sum()))"""),
    ("eth_chain_window", ENV + """
state, _ = jax.jit(env.reset)(key, params)
nua, in_chain = jax.jit(env.chain_window)(state.dag, state.public)
print(int(nua.sum()), int(in_chain.sum()))"""),
    ("eth_uncle_select", ENV + """
state, _ = jax.jit(env.reset)(key, params)
def f(dag, head):
    cand = env.uncle_candidates(dag, head, dag.exists(), dag.exists())
    return env.select_uncles(dag, cand, dag.miner == 0)
idx, valid = jax.jit(f)(state.dag, state.public)
print(idx.tolist(), valid.tolist())"""),
    ("eth_single_step", ENV + """
state, obs = jax.jit(env.reset)(key, params)
step = jax.jit(env.step)
state, obs, r, d, info = step(state, jnp.int32(0), params)
print(float(r), bool(d))"""),
    ("eth_32steps_nojit_scan", ENV + """
# 32 python-loop steps through the jitted single-step kernel: same math
# as the scan, no lax.scan around it
state, obs = jax.jit(env.reset)(key, params)
step = jax.jit(env.step)
for i in range(32):
    state, obs, r, d, info = step(state, jnp.int32(i % env.n_actions), params)
print(float(jnp.asarray(r)))"""),
    ("eth_scan_1env", ENV + """
pol = env.policies["fn19"]
stats = env.episode_stats(key, params, pol, 64)
print(float(stats["episode_progress"]))"""),
    ("eth_scan_64env", ENV + """
pol = env.policies["fn19"]
keys = jax.random.split(key, 64)
f = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, pol, 64)))
stats = jax.block_until_ready(f(keys))
print(float(stats["episode_progress"].mean()))"""),
    ("eth_scan_honest", ENV + """
# same scan, honest policy: separates "fn19 policy path" from the scan
pol = env.policies["honest"]
keys = jax.random.split(key, 64)
f = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, pol, 64)))
stats = jax.block_until_ready(f(keys))
print(float(stats["episode_progress"].mean()))"""),
    ("eth_scan_4096_full", ENV + """
# the failing bench shape (smallest rung): 4096 envs, 256-step hint
env = EthereumSSZ("byzantium", max_steps_hint=256)
params = make_params(alpha=0.35, gamma=0.5, max_steps=248)
pol = env.policies["fn19"]
keys = jax.random.split(key, 4096)
f = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, pol, 256)))
stats = jax.block_until_ready(f(keys))
print(float(stats["episode_progress"].mean()))"""),
    ("bk_scan_64env_control", """
from cpr_tpu.envs.bk import BkSSZ
from cpr_tpu.params import make_params
env = BkSSZ(k=8, incentive_scheme="constant", max_steps_hint=64)
params = make_params(alpha=0.35, gamma=0.5, max_steps=56)
pol = env.policies["get-ahead"]
keys = jax.random.split(jax.random.PRNGKey(0), 64)
f = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, pol, 64)))
stats = jax.block_until_ready(f(keys))
print(float(stats["episode_progress"].mean()))"""),
]

if __name__ == "__main__":
    limit = int(sys.argv[1]) if len(sys.argv) > 1 else None
    run_candidates(CANDIDATES, limit)
