"""Bisect the axon-TPU ethereum-env kernel fault, one candidate per child.

The round-3 chip session showed `EthereumSSZ.episode_stats` faulting the
TPU device at EVERY batch size (65536/16384/4096 envs) while the bk and
tailstorm DAG-tensor envs ran fine — so the fault is a construct the
ethereum env uses and they don't, not memory pressure.  Three stages
(historically three scripts; `--stage` selects one, the findings are in
docs/TPU_SESSION_r03.md):

1. construct walk-up: reset, chain_window (the unrolled uncle-window
   ancestor walk), uncle selection, a single step, then scans of growing
   size, with a bk scan as the known-good control.  Finding: every
   construct passes at 64 envs / capacity 72; the crash needs the full
   bench shape.
2. shape grid + construct stubs: separates env count, DAG capacity,
   scan length, policy; stubs chain_window / select_uncles at the
   crashing shape.  Finding: the fault needs BOTH axes large (4096 x
   capacity 72 passes, 256 x 264 passes, 1024 x 264 crashes).
3. one-at-a-time toggles at the minimal crasher (1024 envs x hint 256):
   scan length, policy, and each ethereum-specific kernel.  Control
   (the unmodified crasher) runs LAST.

Same harness discipline as tools/tpu_vi_bisect.py: each candidate runs
in a watchdog-bounded subprocess; stop at the first CRASH/HANG so a
wedged chip isn't hammered.

Usage: python tools/tpu_eth_bisect.py [--stage {1,2,3}] [max_candidates]
"""

import argparse
import sys

# run as a script from anywhere: the tools dir is sys.path[0] only for
# direct execution, so resolve it explicitly
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from bisect_common import run_candidates  # noqa: E402

ENV = """
from cpr_tpu.envs.ethereum import EthereumSSZ
from cpr_tpu.params import make_params
env = EthereumSSZ("byzantium", max_steps_hint=64)
params = make_params(alpha=0.35, gamma=0.5, max_steps=56)
key = jax.random.PRNGKey(0)
"""


def scan(n_envs, hint, n_steps, policy="fn19", stub=""):
    """One vmapped episode_stats scan at an arbitrary (envs, capacity,
    steps, policy) point, optionally with a construct stubbed out."""
    return f"""
from cpr_tpu.envs.ethereum import EthereumSSZ
from cpr_tpu.params import make_params
env = EthereumSSZ("byzantium", max_steps_hint={hint})
params = make_params(alpha=0.35, gamma=0.5, max_steps={hint} - 8)
{stub}
pol = env.policies["{policy}"]
keys = jax.random.split(jax.random.PRNGKey(0), {n_envs})
f = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, pol, {n_steps})))
stats = jax.block_until_ready(f(keys))
print(float(stats["episode_progress"].mean()))"""


STUB_WINDOW = """
_B = env.capacity
def _stub_window(dag, head):
    z = jnp.zeros((_B,), jnp.bool_)
    return z, z.at[jnp.maximum(head, 0)].set(head >= 0)
env.chain_window = _stub_window"""

STUB_SELECT = """
def _stub_select(dag, cand_mask, own_mask):
    idx = jnp.zeros((env.max_uncles,), jnp.int32)
    return idx, jnp.zeros((env.max_uncles,), jnp.bool_)
env.select_uncles = _stub_select"""

STAGE1 = [
    ("baseline_sum", "print(int(jnp.arange(8).sum()))"),
    ("eth_reset", ENV + """
state, obs = jax.jit(env.reset)(key, params)
print(float(jnp.asarray(obs).sum()))"""),
    ("eth_reset_vmap", ENV + """
keys = jax.random.split(key, 256)
state, obs = jax.jit(jax.vmap(lambda k: env.reset(k, params)))(keys)
print(float(jnp.asarray(obs).sum()))"""),
    ("eth_chain_window", ENV + """
state, _ = jax.jit(env.reset)(key, params)
nua, in_chain = jax.jit(env.chain_window)(state.dag, state.public)
print(int(nua.sum()), int(in_chain.sum()))"""),
    ("eth_uncle_select", ENV + """
state, _ = jax.jit(env.reset)(key, params)
def f(dag, head):
    cand = env.uncle_candidates(dag, head, dag.exists(), dag.exists())
    return env.select_uncles(dag, cand, dag.miner == 0)
idx, valid = jax.jit(f)(state.dag, state.public)
print(idx.tolist(), valid.tolist())"""),
    ("eth_single_step", ENV + """
state, obs = jax.jit(env.reset)(key, params)
step = jax.jit(env.step)
state, obs, r, d, info = step(state, jnp.int32(0), params)
print(float(r), bool(d))"""),
    ("eth_32steps_nojit_scan", ENV + """
# 32 python-loop steps through the jitted single-step kernel: same math
# as the scan, no lax.scan around it
state, obs = jax.jit(env.reset)(key, params)
step = jax.jit(env.step)
for i in range(32):
    state, obs, r, d, info = step(state, jnp.int32(i % env.n_actions), params)
print(float(jnp.asarray(r)))"""),
    ("eth_scan_1env", ENV + """
pol = env.policies["fn19"]
stats = env.episode_stats(key, params, pol, 64)
print(float(stats["episode_progress"]))"""),
    ("eth_scan_64env", scan(64, 64, 64)),
    # same scan, honest policy: separates "fn19 policy path" from the scan
    ("eth_scan_honest", scan(64, 64, 64, policy="honest")),
    # the failing bench shape (smallest rung): 4096 envs, 256-step hint
    ("eth_scan_4096_full", scan(4096, 256, 256)),
    ("bk_scan_64env_control", """
from cpr_tpu.envs.bk import BkSSZ
from cpr_tpu.params import make_params
env = BkSSZ(k=8, incentive_scheme="constant", max_steps_hint=64)
params = make_params(alpha=0.35, gamma=0.5, max_steps=56)
pol = env.policies["get-ahead"]
keys = jax.random.split(jax.random.PRNGKey(0), 64)
f = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, pol, 64)))
stats = jax.block_until_ready(f(keys))
print(float(stats["episode_progress"].mean()))"""),
]

STAGE2 = [
    # axis: env count at small capacity
    ("envs4096_hint64", scan(4096, 64, 64)),
    # axis: capacity at small env count
    ("envs256_hint256", scan(256, 256, 256)),
    # axis: middle ground
    ("envs1024_hint256", scan(1024, 256, 256)),
    ("envs4096_hint128", scan(4096, 128, 128)),
    # the crashing shape, honest policy (is it the fn19 path?)
    ("crash_shape_honest", scan(4096, 256, 256, policy="honest")),
    # the crashing shape with ethereum-specific kernels stubbed
    ("crash_shape_stub_window", scan(4096, 256, 256, stub=STUB_WINDOW)),
    ("crash_shape_stub_select", scan(4096, 256, 256, stub=STUB_SELECT)),
    # control: the known-crashing shape, unmodified (run LAST)
    ("crash_shape_control", scan(4096, 256, 256)),
]

STAGE3 = [
    # axis: scan length (is the 256-step scan needed, or just the shape?)
    ("n1024_h256_scan64", scan(1024, 256, 64)),
    # axis: policy
    ("n1024_h256_honest", scan(1024, 256, 256, policy="honest")),
    # axis: ethereum-specific kernels
    ("n1024_h256_stub_window", scan(1024, 256, 256, stub=STUB_WINDOW)),
    ("n1024_h256_stub_select", scan(1024, 256, 256, stub=STUB_SELECT)),
    ("n1024_h256_stub_both", scan(1024, 256, 256,
                                  stub=STUB_WINDOW + STUB_SELECT)),
    # control: the known crasher, unmodified (LAST)
    ("n1024_h256_control", scan(1024, 256, 256)),
]

STAGES = {1: STAGE1, 2: STAGE2, 3: STAGE3}

if __name__ == "__main__":
    ap = argparse.ArgumentParser(
        description="staged ethereum-env TPU fault bisection")
    ap.add_argument("--stage", type=int, choices=sorted(STAGES),
                    default=1, help="bisection stage (see module doc)")
    ap.add_argument("max_candidates", type=int, nargs="?", default=None)
    args = ap.parse_args()
    run_candidates(STAGES[args.stage], args.max_candidates)
