"""Multi-chip smoke (`make multichip-smoke`).

Proves the sharded hot loops (docs/SCALING.md) end-to-end on a forced
4-device CPU mesh — every child runs under
`XLA_FLAGS=--xla_force_host_platform_device_count=4`, so this passes
on the 1-core CI host with no accelerator:

  1  two supervised `python -m cpr_tpu.serve.server` runs, `--devices 1`
     then `--devices 4`, each flooded with the SAME seeded honest
     episodes over persistent clients, then SIGTERM-drained; each trace
     must pass `trace_summary --validate --expect serve,device_metrics`
     and each drain report must stamp its `n_devices`;
  2  device-count parity: every seeded episode's aggregates (rewards,
     progress, n_steps, relative_reward) must be BIT-IDENTICAL between
     the 1-device and 4-device runs — the sharded lane stepper is the
     same program, just partitioned;
  3  a rollout + netsim child per device count: the same seeds through
     `make_episode_stats_fn(..., mesh=)` and `netsim.Engine(mesh=)`,
     full output pytrees asserted bit-identical across device counts,
     with telemetry spans landing per-device ledger rows under the
     manifest's `devices` config;
  4  all four traces ingest into one perf ledger: `serve_steps_per_sec`
     rows must land at BOTH cfg_devices=1 and cfg_devices=4 (the
     ledger-v4 per-device-count fingerprints), every banked row must
     clear the regression gate, and the perf_report device-scaling
     table must cover the serve metric at both counts.

Usage: python tools/multichip_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
from concurrent.futures import ThreadPoolExecutor

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from cpr_tpu import supervisor  # noqa: E402
from cpr_tpu.perf.gate import gate_row, gate_summary  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402
from cpr_tpu.serve.protocol import ServeClient  # noqa: E402

DEVICES = 4                 # the forced virtual CPU mesh span
MAX_STEPS = 128
LANES = 8                   # divides DEVICES — the sharding contract
BURST = 128
N_CLIENTS = 4
FLOOD_EPISODES = 32
ROLLOUT_STREAMS = 8
NETSIM_ACTIVATIONS = 200
READY_TIMEOUT_S = 300.0
WALL_S = 600.0


def _log(msg):
    print(f"multichip-smoke: {msg}", file=sys.stderr)


def _child_env(workdir, trace, extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count="
                         f"{DEVICES}",
               CPR_TELEMETRY=trace, CPR_DEVICE_METRICS="1",
               CPR_TPU_CACHE=os.path.join(workdir, "cache"))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def _wait_ready(path, proc):
    deadline = time.time() + READY_TIMEOUT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            raise SystemExit(f"server child exited rc={proc.returncode} "
                             f"before becoming ready")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            time.sleep(0.25)
    raise SystemExit(f"server not ready within {READY_TIMEOUT_S:.0f}s")


def _flood_worker(port, seeds, episodes):
    with ServeClient("127.0.0.1", port) as c:
        for s in seeds:
            r = c.request("episode.run", policy="honest", seed=s)
            assert r.get("ok"), f"episode.run(seed={s}): {r}"
            episodes[s] = r["episode"]


def _serve_events(trace, action=None):
    out = []
    with open(trace) as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") == "event" and e.get("name") == "serve" \
                    and (action is None or e.get("action") == action):
                out.append(e)
    return out


def _validate_stream(trace):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, trace, "--validate",
         "--expect", "serve,device_metrics"],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


def _serve_run(work, devices):
    """One supervised server run at `devices`: seeded flood, SIGTERM
    drain, trace validation.  Returns (episodes-by-seed, trace path,
    drain-report detail)."""
    trace = os.path.join(work, f"serve_d{devices}.jsonl")
    if os.path.exists(trace):
        os.remove(trace)
    cmd = [sys.executable, "-m", "cpr_tpu.serve.server",
           "--protocol", "nakamoto", "--max-steps", str(MAX_STEPS),
           "--lanes", str(LANES), "--burst", str(BURST),
           "--devices", str(devices), "--heartbeat-s", "0.5",
           "--ready-file", os.path.join(work, f"ready_d{devices}.json")]

    started = threading.Event()
    box = {}

    def on_start(proc):
        box["proc"] = proc
        started.set()

    def supervise():
        box["attempt"] = supervisor.run_child(
            cmd, wall_timeout_s=WALL_S, quiet_s=20.0, heartbeat_s=1.0,
            env=_child_env(work, trace), cwd=ROOT, on_start=on_start)

    child = threading.Thread(target=supervise)
    child.start()
    episodes = {}
    try:
        if not started.wait(30.0):
            raise SystemExit("run_child never spawned the server")
        ready = _wait_ready(
            os.path.join(work, f"ready_d{devices}.json"), box["proc"])
        port = ready["port"]
        _log(f"server --devices {devices} ready on port {port}")

        per = FLOOD_EPISODES // N_CLIENTS
        with ThreadPoolExecutor(max_workers=N_CLIENTS) as pool:
            jobs = [pool.submit(_flood_worker, port,
                                range(100 + w * per, 100 + (w + 1) * per),
                                episodes)
                    for w in range(N_CLIENTS)]
            for j in jobs:
                j.result()
        box["proc"].send_signal(signal.SIGTERM)
    except BaseException:
        proc = box.get("proc")
        if proc is not None and proc.poll() is None:
            proc.kill()
        raise
    child.join(120.0)
    if child.is_alive():
        raise SystemExit("server child did not drain within 120s")
    attempt = box["attempt"]
    if attempt.status != "ok" or attempt.rc != 0:
        raise SystemExit(f"--devices {devices} child did not exit "
                         f"cleanly (status={attempt.status} "
                         f"rc={attempt.rc})")
    for want in ("start", "admit", "complete", "drain", "report",
                 "stop"):
        if not _serve_events(trace, want):
            raise SystemExit(f"no serve '{want}' event in {trace}")
    _validate_stream(trace)
    reports = _serve_events(trace, "report")
    detail = reports[-1].get("detail") or {}
    if detail.get("n_devices") != devices:
        raise SystemExit(f"drain report stamps n_devices="
                         f"{detail.get('n_devices')}, expected {devices}")
    _log(f"--devices {devices}: {len(episodes)} episodes, drained "
         f"clean, report n_devices={devices}, "
         f"{detail.get('steps_per_sec', 0):,.0f} steps/s")
    return episodes, trace, detail


# the in-process twin of the serve parity run: the same mesh seam
# through make_episode_stats_fn and netsim.Engine, outputs dumped as
# exact JSON for the parent's bit-identity check
_COMPUTE_CHILD = textwrap.dedent("""\
    import json, os

    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    from cpr_tpu import netsim, telemetry
    from cpr_tpu.envs import registry
    from cpr_tpu.network import symmetric_clique
    from cpr_tpu.params import make_params

    devices = int(os.environ["CPR_SMOKE_DEVICES"])
    max_steps = int(os.environ["CPR_SMOKE_MAX_STEPS"])
    streams = int(os.environ["CPR_SMOKE_STREAMS"])
    activations = int(os.environ["CPR_SMOKE_ACTIVATIONS"])

    mesh = None
    if devices > 1:
        from cpr_tpu.parallel import default_mesh
        devs = jax.devices()
        assert len(devs) >= devices, (len(devs), devices)
        mesh = default_mesh(devices=devs[:devices])

    tele = telemetry.current()
    tele.manifest(dict(role="multichip-compute", devices=devices,
                       protocol="nakamoto", streams=streams,
                       max_steps=max_steps))

    env = registry.get_sized("nakamoto", max_steps)
    params = make_params(alpha=0.25, gamma=0.5, max_steps=max_steps)
    fn = env.make_episode_stats_fn(params, env.policies["honest"],
                                   max_steps, chunk=max_steps // 2,
                                   mesh=mesh)
    keys = jax.vmap(jax.random.PRNGKey)(
        jnp.arange(streams, dtype=jnp.uint32))
    with tele.span("multichip:rollout", steps=streams * max_steps):
        stats = jax.block_until_ready(fn(keys))

    net = symmetric_clique(5, activation_delay=30.0,
                           propagation_delay=1.0)
    eng = netsim.Engine(net, protocol="nakamoto",
                        activations=activations, mesh=mesh)
    out = eng.run(list(range(streams)), [30.0] * streams)

    payload = dict(
        devices=devices,
        rollout=jax.tree.map(lambda x: jnp.asarray(x).tolist(), stats),
        netsim={k: out[k].tolist() for k in sorted(out)},
    )
    with open(os.environ["CPR_SMOKE_OUT"], "w") as f:
        json.dump(payload, f, sort_keys=True)
    print("multichip compute child ok:", devices, "device(s)")
""")


def _compute_run(work, devices):
    """Sharded rollout + netsim in a forced-mesh child; returns the
    exact output payload and the trace path."""
    trace = os.path.join(work, f"compute_d{devices}.jsonl")
    out_path = os.path.join(work, f"compute_d{devices}.json")
    for p in (trace, out_path):
        if os.path.exists(p):
            os.remove(p)
    env = _child_env(work, trace, extra={
        "CPR_SMOKE_DEVICES": str(devices),
        "CPR_SMOKE_MAX_STEPS": str(MAX_STEPS),
        "CPR_SMOKE_STREAMS": str(ROLLOUT_STREAMS),
        "CPR_SMOKE_ACTIVATIONS": str(NETSIM_ACTIVATIONS),
        "CPR_SMOKE_OUT": out_path,
    })
    r = subprocess.run([sys.executable, "-c", _COMPUTE_CHILD], env=env,
                       cwd=ROOT, capture_output=True, text=True,
                       timeout=WALL_S)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"compute child (devices={devices}) failed "
                         f"rc={r.returncode}")
    with open(out_path) as f:
        payload = json.load(f)
    _log(f"compute child devices={devices}: rollout "
         f"{ROLLOUT_STREAMS}x{MAX_STEPS} + netsim "
         f"{ROLLOUT_STREAMS}x{NETSIM_ACTIVATIONS} done")
    return payload, trace


def _assert_identical(what, a, b):
    if a != b:
        raise SystemExit(f"{what} NOT bit-identical between 1-device "
                         f"and {DEVICES}-device runs")
    _log(f"{what}: bit-identical across device counts")


def _bank_and_gate(work, traces):
    """All traces into one ledger; serve_steps_per_sec must land at
    both device counts, every banked row must clear the gate, and the
    perf_report scaling table must cover the serve metric."""
    ledger = Ledger(os.path.join(work, "perf_ledger.jsonl"))
    n = sum(ledger.ingest_trace(t) for t in traces)
    records = ledger.records()
    sps = [r for r in records if r.get("metric") == "serve_steps_per_sec"]
    got = {r.get("config", {}).get("cfg_devices") for r in sps}
    if not {1, DEVICES} <= got:
        raise SystemExit(f"serve_steps_per_sec banked at device counts "
                         f"{sorted(got)}, need both 1 and {DEVICES}")
    results = [gate_row(r, records) for r in records]
    summary = gate_summary(results)
    if not summary["ok"]:
        bad = [res for res in results if res["verdict"] == "fail"]
        raise SystemExit(f"multichip perf gate failed: {bad}")

    tools_dir = os.path.dirname(os.path.abspath(__file__))
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import perf_report

    scaling = perf_report.scaling_groups(records)
    covered = [g for g in scaling
               if g["metric"] == "serve_steps_per_sec"
               and {row["devices"] for row in g["rows"]}
               >= {1, DEVICES}]
    if not covered:
        raise SystemExit("perf_report scaling table does not cover "
                         "serve_steps_per_sec at both device counts")
    for line in perf_report.scaling_lines(scaling):
        _log(line)
    return n, summary, covered[0]


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-multichip-smoke"
    os.makedirs(work, exist_ok=True)

    eps_1, trace_s1, _ = _serve_run(work, 1)
    eps_n, trace_sn, _ = _serve_run(work, DEVICES)
    if sorted(eps_1) != sorted(eps_n):
        raise SystemExit("the two serve runs completed different seed "
                         "sets — flood harness bug")
    _assert_identical(f"serve episode aggregates ({len(eps_1)} seeded "
                      f"episodes)", eps_1, eps_n)

    out_1, trace_c1 = _compute_run(work, 1)
    out_n, trace_cn = _compute_run(work, DEVICES)
    _assert_identical("sharded rollout episode stats",
                      out_1["rollout"], out_n["rollout"])
    _assert_identical("sharded netsim outputs",
                      out_1["netsim"], out_n["netsim"])

    n, summary, grp = _bank_and_gate(
        work, [trace_s1, trace_sn, trace_c1, trace_cn])
    top = grp["rows"][-1]
    print(f"multichip-smoke: PASS (serve + rollout + netsim "
          f"bit-identical at 1 vs {DEVICES} devices; banked {n} ledger "
          f"rows incl. serve_steps_per_sec at devices 1 and {DEVICES} "
          f"[{DEVICES}-dev speedup {top['speedup']:.2f}x, efficiency "
          f"{top['efficiency']:.0%}]; gate {summary})")


if __name__ == "__main__":
    main()
