"""Bisect the axon-TPU VI kernel fault, one candidate per subprocess.

Each candidate runs in a watchdog-bounded child (the bench.py pattern:
a crashed worker can wedge backend init for the NEXT process, so the
parent detects both crash-rc and init-hang).  Run when the chip is
healthy; stop at the first crash to avoid wedging it repeatedly.

Usage: python tools/tpu_vi_bisect.py [max_candidates]
"""

import sys

# run as a script from anywhere: the tools dir is sys.path[0] only for
# direct execution, so resolve it explicitly
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from bisect_common import run_candidates  # noqa: E402

CANDIDATES = [
    ("baseline_sum", "print(int(jnp.arange(8).sum()))"),
    ("segment_sum_small", """
import numpy as np
src = jnp.asarray(np.random.default_rng(0).integers(0, 1000, 5000), jnp.int32)
out = jax.ops.segment_sum(jnp.ones(5000, jnp.float32), src, num_segments=1000)
print(float(out.sum()))"""),
    ("argmax_neginf", """
x = jnp.where(jnp.arange(4096) % 3 == 0,
              -jnp.inf, jnp.arange(4096, dtype=jnp.float32)).reshape(512, 8)
print(int(jnp.argmax(x, axis=1).sum()))"""),
    ("gather_large", """
import numpy as np
v = jnp.arange(100000, dtype=jnp.float32)
idx = jnp.asarray(np.random.default_rng(0).integers(0, 100000, 500000), jnp.int32)
print(float(v[idx].sum()))"""),
    ("while_loop_sweep", """
def body(c):
    v, i = c
    v2 = jax.ops.segment_sum(v[jnp.arange(1000) % 100] * 0.5,
                             jnp.arange(1000) % 100, num_segments=100)[
        jnp.arange(1000) % 100]
    return v2, i + 1
v, i = jax.lax.while_loop(lambda c: c[1] < 50, body,
                          (jnp.ones(1000, jnp.float32), 0))
print(int(i), float(v.sum()))"""),
    ("scan_sweep", """
# the while_loop candidate's scan twin: same segment_sum sweep body,
# fixed trip count — separates "loop construct" from "sweep body"
def body(c, _):
    v = jax.ops.segment_sum(c[jnp.arange(1000) % 100] * 0.5,
                            jnp.arange(1000) % 100, num_segments=100)[
        jnp.arange(1000) % 100]
    return v, None
v, _ = jax.lax.scan(body, jnp.ones(1000, jnp.float32), None, length=50)
print(float(v.sum()))"""),
    ("argmax_inf_while", """
# masked argmax with -inf inside a while_loop (the _greedy_backup shape)
def body(c):
    q, i = c
    qm = jnp.where(jnp.arange(8) % 2 == 0, q, -jnp.inf)
    a = jnp.argmax(qm.reshape(64, 8)[:, :], axis=1)
    return q + a.sum() * 1e-9, i + 1
q, i = jax.lax.while_loop(lambda c: c[1] < 50, body,
                          (jnp.ones(512, jnp.float32).reshape(64, 8), 0))
print(int(i))"""),
    ("vi_fc16_small", """
from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.models import Fc16BitcoinSM
tm = ptmdp(Compiler(Fc16BitcoinSM(alpha=0.3, gamma=0.5,
                                  maximum_fork_length=8)).mdp(),
           horizon=20).tensor()
vi = tm.value_iteration(stop_delta=1e-6)
print(int(vi["vi_iter"]))"""),
    ("vi_fc16_small_chunked", """
# the workaround candidate: same sweeps, no device while_loop
from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.models import Fc16BitcoinSM
tm = ptmdp(Compiler(Fc16BitcoinSM(alpha=0.3, gamma=0.5,
                                  maximum_fork_length=8)).mdp(),
           horizon=20).tensor()
vi = tm.value_iteration(stop_delta=1e-6, impl="chunked")
print(int(vi["vi_iter"]))"""),
    ("vi_fc16_pt_chunked", """
# BASELINE config-5 adjacent size (fc16/PT table), chunked impl
from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.models import Fc16BitcoinSM
tm = ptmdp(Compiler(Fc16BitcoinSM(alpha=0.33, gamma=0.7,
                                  maximum_fork_length=25)).mdp(),
           horizon=60).tensor()
vi = tm.value_iteration(stop_delta=1e-5, impl="chunked")
print(int(vi["vi_iter"]), round(float(vi["vi_delta"]), 8))"""),
    ("vi_ghostdag_c5", """
from cpr_tpu.mdp import ptmdp
from cpr_tpu.mdp.generic.native import compile_native
tm = ptmdp(compile_native("ghostdag", k=2, alpha=0.33, gamma=0.5,
                          collect_garbage="simple", dag_size_cutoff=5),
           horizon=20).tensor()
vi = tm.value_iteration(stop_delta=1e-6)
print(int(vi["vi_iter"]))"""),
    ("vi_ghostdag_c7_chunked", """
from cpr_tpu.mdp import ptmdp
from cpr_tpu.mdp.generic.native import compile_native
tm = ptmdp(compile_native("ghostdag", k=2, alpha=0.33, gamma=0.5,
                          collect_garbage="simple", dag_size_cutoff=7),
           horizon=100).tensor()
vi = tm.value_iteration(stop_delta=1e-5, impl="chunked")
print(int(vi["vi_iter"]))"""),
    # LAST: the one-call while_loop solve — if the whole solve exceeds
    # the axon worker's ~60-75 s per-call ceiling it kills the worker
    # (tools/tpu_limit_probe.py), which is the round-2 "VI kernel
    # fault" root cause
    ("vi_ghostdag_c7", """
from cpr_tpu.mdp import ptmdp
from cpr_tpu.mdp.generic.native import compile_native
tm = ptmdp(compile_native("ghostdag", k=2, alpha=0.33, gamma=0.5,
                          collect_garbage="simple", dag_size_cutoff=7),
           horizon=100).tensor()
vi = tm.value_iteration(stop_delta=1e-5)
print(int(vi["vi_iter"]))"""),
]

if __name__ == "__main__":
    limit = int(sys.argv[1]) if len(sys.argv) > 1 else None
    run_candidates(CANDIDATES, limit, timeout=240.0)
