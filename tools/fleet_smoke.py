"""Fleet-resilience smoke (`make fleet-smoke`).

Proves the cpr_tpu/serve fleet contract — SLO-aware admission control,
in-band load shedding, and deterministic replica failover — end to end
on CPU, the way the SERVING.md runbook describes it:

  1  launch `python -m cpr_tpu.serve.router --replicas 2` (each replica
     a supervised server child with its own telemetry sink and an armed
     `replica` fault site) with a deliberately tiny capacity
     (4 lanes + max-queue 4 per replica) and
     CPR_FAULT_INJECT=kill@replica=1 in the environment;
  2  flood it with ~32 concurrent seeded `episode.run` clients through
     `ServeClient.call_with_retry`.  Replica 1 dies at its first burst
     under load; the router requeues its in-flight sessions onto
     replica 0 (seed replay), and the overload against the halved fleet
     forces in-band `shed: queue_full` refusals that the clients absorb
     via the retry_after contract — zero client hangs, zero errors;
  3  every reply (including the requeued and the router-seeded ones) is
     checked byte-for-byte against an in-process `env.rollout` of the
     same seed — the bit-identity failover guarantee;
  4  the killed replica warm-restarts (fault env stripped: one-shot),
     rejoins the fleet, and serves a post-restart round; router stats
     must show the requeue/shed/restart accounting;
  5  mid-flood, scrape the v14 health plane both ways: HTTP GET on the
     router's and replicas' `--metrics-port` endpoints (every line
     checked against the Prometheus text-format grammar) and the
     in-band `metrics.scrape` op; after the load, prove the fleet
     latency merge is exact by re-merging the per-replica raw bucket
     payloads by hand and comparing the router's fleet board
     byte-for-byte (bucket-sum, never quantile-of-quantiles);
  6  a router-initiated drain, then the evidence: the v9 `route` trail
     (replica_up/down, requeue, drain, stop), `admission` shed events,
     and at least one v14 `alert` fired by the chaos leg validate via
     `trace_summary --validate --expect admission,route,serve,request,
     alert`; the killed replica's crash flight recorder left a
     schema-valid blackbox dump in the workdir; `trace_stitch` pairs
     at least one request across client+router+replica streams with a
     `route` leg; and the drain reports' per-class p99 + shed-rate
     rows plus the router's `fleet_p99_s` rows ingest into a fresh
     perf ledger and clear the gate.

Usage: python tools/fleet_smoke.py [workdir]   (default /tmp/...)
"""

import glob
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from cpr_tpu import telemetry  # noqa: E402
from cpr_tpu.perf.gate import gate_row, gate_summary  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402
from cpr_tpu.serve.protocol import ServeClient  # noqa: E402

# tiny geometry: capacity 4 lanes + 4 queue slots per replica, so a
# 32-client flood against a fleet that just lost half its replicas is
# guaranteed to shed — and 16-step episodes keep every phase fast
MAX_STEPS = 16
LANES = 4
BURST = 8
MAX_QUEUE = 4
REPLICAS = 2
N_SEEDED = 28
N_SEEDLESS = 4
SEED0 = 9001
ROUTER_SEED_BASE = 1 << 21  # router-stamped seeds live above this
READY_TIMEOUT_S = 600.0
FLOOD_TIMEOUT_S = 300.0
# a tight SLO scales the alert windows down (fast page window floors
# at 5 s), so the chaos leg's shed burst fires a v14 alert in-run
SLO_S = 0.5

# Prometheus text format 0.0.4: every non-comment line is one sample
_PROM_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})? '
    r'(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|\+Inf|-Inf|NaN)$')


def _log(msg):
    print(f"fleet-smoke: {msg}", file=sys.stderr)


def _router_cmd(workdir):
    return [sys.executable, "-m", "cpr_tpu.serve.router",
            "--replicas", str(REPLICAS), "--protocol", "nakamoto",
            "--max-steps", str(MAX_STEPS), "--lanes", str(LANES),
            "--burst", str(BURST), "--max-queue", str(MAX_QUEUE),
            "--heartbeat-s", "0.5", "--workdir", workdir,
            "--slo-s", str(SLO_S), "--metrics-port", "0",
            "--ready-file", os.path.join(workdir, "router.json")]


def _router_env(workdir, trace):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CPR_TELEMETRY=trace, CPR_DEVICE_METRICS="1",
               CPR_FAULT_INJECT="kill@replica=1",
               CPR_BLACKBOX_DIR=workdir,
               CPR_RUN_ID=telemetry.run_id(),
               CPR_TPU_CACHE=os.path.join(workdir, "cache"))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _wait_ready(path, proc, log_path):
    deadline = time.time() + READY_TIMEOUT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            tail = open(log_path).read()[-4000:]
            raise SystemExit(f"router exited rc={proc.returncode} before "
                             f"becoming ready\n{tail}")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            time.sleep(0.25)
    raise SystemExit(f"router not ready within {READY_TIMEOUT_S:.0f}s")


def _episode_refs(seeds):
    """In-process ground truth: the episode aggregates `episode.run`
    must reproduce for each seed — captured, like the engine does, at
    the first done of rollout(PRNGKey(seed))."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cpr_tpu.envs import registry
    from cpr_tpu.params import make_params

    env = registry.get_sized("nakamoto", MAX_STEPS)
    params = make_params(alpha=0.25, gamma=0.5, max_steps=MAX_STEPS)
    policy = env.policies["honest"]

    batch = jax.jit(jax.vmap(
        lambda k: env.rollout(k, params, policy, MAX_STEPS)))
    keys = jax.vmap(jax.random.PRNGKey)(
        jnp.asarray(list(seeds), jnp.uint32))
    _, _, _, done, info = batch(keys)
    done = np.asarray(done)
    info = {k: np.asarray(v) for k, v in info.items()}
    refs = {}
    for row, s in enumerate(seeds):
        idx = int(np.argmax(done[row]))
        assert done[row][idx], f"seed {s}: no done within {MAX_STEPS}"
        att = float(info["episode_reward_attacker"][row, idx])
        dfn = float(info["episode_reward_defender"][row, idx])
        refs[int(s)] = dict(
            reward_attacker=att, reward_defender=dfn,
            progress=float(info["episode_progress"][row, idx]),
            n_steps=int(info["episode_n_steps"][row, idx]),
            relative_reward=(att / (att + dfn) if (att + dfn) else 0.0))
    return refs


def _check_episodes(replies, label):
    """Bit-identity: every reply must equal the rollout reference of
    its (possibly router-stamped) seed, field for field."""
    refs = _episode_refs(sorted({r["seed"] for r in replies}))
    for r in replies:
        ref = refs[r["seed"]]
        got = r["episode"]
        for k, want in ref.items():
            if got.get(k) != want:
                raise SystemExit(
                    f"{label}: seed {r['seed']} field {k} diverged "
                    f"from rollout: got {got.get(k)!r}, want {want!r}")
    _log(f"{label}: {len(replies)} episodes bit-identical to rollout")


def _flood_worker(port, seed, sleeps, lock):
    with ServeClient("127.0.0.1", port, timeout=120.0) as c:
        def sleep(s):
            with lock:
                sleeps.append(s)
            time.sleep(s)

        req = dict(policy="honest")
        if seed is not None:
            req["seed"] = seed
        r = c.call_with_retry("episode.run", max_attempts=10,
                              sleep=sleep, **req)
        assert r.get("ok"), f"episode.run(seed={seed}): {r}"
        return r


def _assert_prometheus_text(body, family_prefix, label):
    """The same line-by-line grammar check the tier-1 monitor tests
    pin: comments or well-formed samples only, no Python `None`."""
    if "None" in body:
        raise SystemExit(f"{label}: Python None leaked into the "
                         f"Prometheus exposition")
    samples = 0
    for line in body.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        if not _PROM_SAMPLE_RE.match(line):
            raise SystemExit(f"{label}: bad Prometheus sample line: "
                             f"{line!r}")
        samples += 1
    if not any(ln.startswith(family_prefix) for ln in body.splitlines()):
        raise SystemExit(f"{label}: no {family_prefix}* family in the "
                         f"exposition")
    return samples


def _scrape_http(ready):
    """Mid-flood HTTP scrape of every live exposition endpoint: the
    router's own and each replica's (a replica mid-kill may refuse —
    at least one replica endpoint must answer)."""
    n = _assert_prometheus_text(
        _http_get(ready["metrics_port"]), "cpr_router_", "router scrape")
    _log(f"HTTP scrape: router exposed {n} samples")
    ok = 0
    for idx, port in sorted((ready.get("replica_metrics_ports")
                             or {}).items()):
        if port is None:
            continue
        try:
            body = _http_get(port)
        except OSError:
            continue  # the chaos leg may have just killed this one
        _assert_prometheus_text(body, "cpr_serve_", f"replica {idx}")
        if f'replica="{idx}"' not in body:
            raise SystemExit(f"replica {idx} exposition lacks its "
                             f"replica const label")
        ok += 1
    if not ok:
        raise SystemExit("no replica metrics endpoint answered the "
                         "mid-flood scrape")
    return ok


def _http_get(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
        if r.status != 200:
            raise SystemExit(f"metrics endpoint returned {r.status}")
        ctype = r.headers.get("Content-Type", "")
        if "version=0.0.4" not in ctype:
            raise SystemExit(f"wrong exposition content type: {ctype}")
        return r.read().decode("utf-8")


def _scrape_inband(port):
    """The in-band path: `metrics.scrape` answered at the router with
    its registry JSON plus the freshly merged fleet view."""
    with ServeClient("127.0.0.1", port) as c:
        r = c.request("metrics.scrape")
    if not r.get("ok"):
        raise SystemExit(f"metrics.scrape refused: {r}")
    m = r["metrics"]
    if m["namespace"] != "cpr_router" or "counters" not in m:
        raise SystemExit(f"unexpected metrics.scrape payload: "
                         f"{sorted(m)}")
    fleet = r["fleet"]
    for key in ("latencies", "latencies_raw", "p99_s"):
        if key not in fleet:
            raise SystemExit(f"metrics.scrape fleet view lacks {key}")
    return fleet


def _flood(port, ready):
    """The chaos window: concurrent seeded load that both triggers the
    armed kill@replica=1 (first burst under load) and overloads the
    surviving capacity into in-band sheds.  The health plane is
    scraped both ways WHILE the flood is in flight — live exposition
    under load is the thing being proven."""
    sleeps, lock = [], threading.Lock()
    seeds = [SEED0 + i for i in range(N_SEEDED)] + [None] * N_SEEDLESS
    with ThreadPoolExecutor(max_workers=len(seeds)) as pool:
        jobs = [pool.submit(_flood_worker, port, s, sleeps, lock)
                for s in seeds]
        n_http = _scrape_http(ready)
        fleet = _scrape_inband(port)
        _log(f"mid-flood scrape: router + {n_http} replica HTTP "
             f"endpoints grammar-clean; in-band fleet families "
             f"{sorted(fleet['p99_s']) or '(none yet)'}")
        deadline = time.time() + FLOOD_TIMEOUT_S
        replies = [j.result(timeout=max(1.0, deadline - time.time()))
                   for j in jobs]  # a timeout here IS a client hang
    for want, r in zip(seeds, replies):
        if want is not None and r["seed"] != want:
            raise SystemExit(f"seeded run came back as {r['seed']}")
    stamped = [r["seed"] for w, r in zip(seeds, replies) if w is None]
    if len(stamped) != N_SEEDLESS or \
            any(s < ROUTER_SEED_BASE for s in stamped):
        raise SystemExit(f"router did not stamp seedless runs from its "
                         f"own range: {stamped}")
    return replies, sleeps


def _post_restart_flood(port, sleeps):
    """The rejoin must be proven by served work, not just by
    state == "up": concurrent rounds of 8 clients until replica 1's
    own report shows episodes (least-loaded routing spills onto it
    once replica 0's lanes fill), which also makes its drain report
    bank a non-degenerate throughput row."""
    lock = threading.Lock()
    replies = []
    for round_ in range(5):
        base = 9500 + 8 * round_
        with ThreadPoolExecutor(max_workers=8) as pool:
            jobs = [pool.submit(_flood_worker, port, base + i,
                                sleeps, lock) for i in range(8)]
            deadline = time.time() + FLOOD_TIMEOUT_S
            replies += [j.result(timeout=max(1.0, deadline - time.time()))
                        for j in jobs]
        rep1 = _stats(port)["replicas"].get("1", {})
        if (rep1.get("report") or {}).get("episodes"):
            return replies
    raise SystemExit("restarted replica 1 served no episodes across 5 "
                     "post-restart rounds")


def _stats(port):
    with ServeClient("127.0.0.1", port) as c:
        r = c.request("stats")
        assert r.get("ok"), r
        return r


def _wait_replica_back(port, timeout_s=READY_TIMEOUT_S):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        st = _stats(port)
        state = st["router"]["replica_state"]
        if all(v == "up" for v in state.values()):
            return st
        time.sleep(1.0)
    raise SystemExit(f"killed replica not back up within {timeout_s:.0f}s: "
                     f"{_stats(port)['router']}")


def _events(path, name, action=None):
    out = []
    try:
        f = open(path)
    except OSError:
        return out
    with f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") == "event" and e.get("name") == name \
                    and (action is None or e.get("action") == action):
                out.append(e)
    return out


def _check_route_trail(router_trace, stats):
    downs = _events(router_trace, "route", "replica_down")
    if not any(e.get("replica") == 1 for e in downs):
        raise SystemExit(f"no replica_down for replica 1: {downs}")
    requeues = _events(router_trace, "route", "requeue")
    if not requeues:
        raise SystemExit("router trace has no requeue events — the "
                         "kill produced no failover")
    ups = _events(router_trace, "route", "replica_up")
    if len(ups) < REPLICAS + 1:
        raise SystemExit(f"expected >= {REPLICAS + 1} replica_up "
                         f"(initial fleet + warm restart), got {len(ups)}")
    for want in ("drain", "stop"):
        if not _events(router_trace, "route", want):
            raise SystemExit(f"no route '{want}' event in router trace")
    r = stats["router"]
    if r["requeued"] < 1 or r["restarts"].get("1", 0) < 1:
        raise SystemExit(f"router stats missing the failover accounting: "
                         f"{r}")
    if r["requeued"] != len(requeues):
        raise SystemExit(f"stats requeued={r['requeued']} but the route "
                         f"trail has {len(requeues)} requeue events")
    return len(requeues)


def _check_sheds(replica_traces, stats, sleeps):
    adm = [e for p in replica_traces for e in _events(p, "admission")]
    if not adm:
        raise SystemExit("no admission events: the overload produced "
                         "no sheds (capacity too large for the flood?)")
    bad = [e for e in adm if not (isinstance(e.get("retry_after_s"),
                                             (int, float))
                                  and e["retry_after_s"] > 0)]
    if bad:
        raise SystemExit(f"admission events without a positive "
                         f"retry_after_s: {bad[:3]}")
    per = stats["replicas"]
    stat_sheds = sum(v.get("sheds", 0) for v in per.values()
                     if v.get("state") == "up")
    if stat_sheds < 1:
        raise SystemExit(f"stats report no sheds: {per}")
    if not sleeps:
        raise SystemExit("clients absorbed sheds without a single "
                         "backoff sleep — retry_after was not honored")
    return len(adm)


def _check_fleet_merge(stats):
    """The fleet merge must be EXACT: re-merge the per-replica raw
    bucket payloads from one stats reply by hand and compare the
    router's fleet board from the same reply byte-for-byte.  A
    quantile-of-quantiles shortcut (or a double-count from a carried
    board) cannot survive this."""
    from cpr_tpu.latency import LatencyBoard

    by_hand = LatencyBoard()
    for rep in stats["replicas"].values():
        raw = rep.get("latencies_raw")
        if isinstance(raw, dict):
            by_hand.merge_dict(raw)
    fleet_raw = stats["fleet"]["latencies_raw"]
    if by_hand.to_dict() != fleet_raw:
        raise SystemExit("router fleet board diverges from the "
                         "merged-by-hand reference")
    if "episode.run" not in fleet_raw or \
            fleet_raw["episode.run"]["count"] < 1:
        raise SystemExit(f"fleet board has no episode.run latencies: "
                         f"{sorted(fleet_raw)}")
    snap = stats["fleet"]["latencies"]["episode.run"]
    ref = by_hand.get("episode.run").snapshot()
    if snap != ref:
        raise SystemExit(f"fleet p99 snapshot diverges from the "
                         f"by-hand merge: {snap} vs {ref}")
    return fleet_raw["episode.run"]["count"]


def _check_alerts(replica_traces, stats):
    """The chaos leg must fire at least one typed v14 alert (the shed
    burst against the halved fleet burns the 2% shed budget at >4x on
    the fast window), schema-complete, and the drain reports carry the
    alerts block."""
    alerts = [e for p in replica_traces for e in _events(p, "alert")]
    if not alerts:
        raise SystemExit("no v14 alert event in any replica trace — "
                         "the chaos leg burned no error budget?")
    for e in alerts:
        missing = [k for k in ("signal", "severity", "window_s",
                               "value", "budget", "burn_rate")
                   if k not in e]
        if missing:
            raise SystemExit(f"alert event missing {missing}: {e}")
    if not any(e["signal"] == "shed_rate" for e in alerts):
        raise SystemExit(f"no shed_rate alert among "
                         f"{[e['signal'] for e in alerts]}")
    reported = [v.get("alerts") for v in stats["replicas"].values()
                if isinstance(v.get("alerts"), dict)]
    if not any(a.get("fired", 0) >= 1 for a in reported):
        raise SystemExit(f"no replica stats carries a fired alert "
                         f"count: {reported}")
    return len(alerts)


def _check_blackbox(workdir):
    """The killed replica's flight recorder must have dumped: a
    schema-valid blackbox whose header names the InjectedKill."""
    dumps = sorted(glob.glob(os.path.join(workdir, "blackbox-*.jsonl")))
    if not dumps:
        raise SystemExit("no blackbox dump in the workdir — the "
                         "killed replica's flight recorder is dark")
    reasons = []
    for p in dumps:
        with open(p) as f:
            man = json.loads(f.readline())
        if man.get("kind") != "manifest" or not man.get("backend"):
            raise SystemExit(f"{p}: blackbox header is not a "
                             f"backend-bearing manifest")
        reasons.append(man.get("config", {}).get("reason"))
        _validate_stream(p, expect=None)
    if not any(r == "serve:InjectedKill" for r in reasons):
        raise SystemExit(f"no blackbox names the injected kill: "
                         f"{reasons}")
    return reasons


def _check_fleet_report(router_trace):
    """The router's drain-time fleet_report: the fleet-merged per-
    family p99 the perf ledger lifts into fleet_p99_s rows."""
    reports = _events(router_trace, "serve", "fleet_report")
    if not reports:
        raise SystemExit("router trace has no fleet_report event")
    fleet = (reports[-1].get("detail") or {}).get("fleet_p99_s")
    if not isinstance(fleet, dict) or "episode.run" not in fleet:
        raise SystemExit(f"fleet_report lacks fleet_p99_s[episode.run]: "
                         f"{reports[-1]}")
    if not (isinstance(fleet["episode.run"], float)
            and fleet["episode.run"] > 0):
        raise SystemExit(f"degenerate fleet p99: {fleet}")
    return fleet


def _check_reports(replica_traces):
    """At least one drain report must carry the per-class tail and a
    nonzero shed rate (the overloaded survivor's report)."""
    details = []
    for p in replica_traces:
        for e in _events(p, "serve", "report"):
            d = e.get("detail")
            if isinstance(d, dict):
                details.append(d)
    if not details:
        raise SystemExit("no drain reports in the replica traces")
    classy = [d for d in details
              if isinstance(d.get("class_p99_s"), dict)
              and d["class_p99_s"].get("normal", 0) > 0]
    if not classy:
        raise SystemExit(f"no report carries class_p99_s['normal']: "
                         f"{[sorted(d) for d in details]}")
    if not any(d.get("shed_rate", 0) > 0 for d in details):
        raise SystemExit("no report carries a nonzero shed_rate")
    return details


def _merge_streams(workdir, paths):
    from cpr_tpu import resilience

    parts = []
    for p in paths:
        try:
            with open(p) as f:
                parts.append(f.read())
        except OSError:
            pass
    merged = os.path.join(workdir, "merged.jsonl")
    resilience.atomic_write_text(merged, "".join(parts))
    return merged


def _validate_stream(trace,
                     expect="admission,route,serve,request,alert"):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    cmd = [sys.executable, tool, trace, "--validate"]
    if expect:
        cmd += ["--expect", expect]
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


def _check_stitch(streams):
    """trace_stitch across client + router + replica streams must pair
    at least one request on all three sides — i.e. with the router-hop
    `route` leg in its breakdown."""
    tools_dir = os.path.dirname(os.path.abspath(__file__))
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import trace_stitch

    st = trace_stitch.stitch(streams)
    routed = [t for t in st["traces"]
              if t.get("orphan") is None
              and t["breakdown"].get("route_s") is not None]
    if not routed:
        raise SystemExit("trace_stitch found no request with a router "
                         "hop across the captured streams")
    return len(routed), len(st["traces"])


# every drain report must land these rows; per-class p99 rows ride on
# the same serve_p99_s metric with a cfg_class fingerprint, and the
# router's fleet_report lands the fleet-merged per-family tail
_REQUIRED_METRICS = ("serve_steps_per_sec", "serve_p99_s",
                     "serve_shed_rate", "fleet_p99_s")


def _bank_and_gate(workdir, traces):
    ledger = Ledger(os.path.join(workdir, "perf_ledger.jsonl"))
    n = sum(ledger.ingest_trace(p) for p in traces)
    records = ledger.records()
    results = []
    for metric in _REQUIRED_METRICS:
        rows = [r for r in records if r.get("metric") == metric]
        if not rows:
            raise SystemExit(f"no {metric} row reached the ledger")
        results.extend(gate_row(r, records) for r in rows)
    per_class = [r for r in records if r.get("metric") == "serve_p99_s"
                 and r.get("config", {}).get("cfg_class")]
    if not per_class:
        raise SystemExit("no per-class serve_p99_s row (cfg_class) "
                         "reached the ledger")
    fleet_rows = [r for r in records if r.get("metric") == "fleet_p99_s"]
    if not any(r.get("config", {}).get("cfg_family") == "episode.run"
               for r in fleet_rows):
        raise SystemExit(f"no fleet_p99_s row for episode.run reached "
                         f"the ledger: "
                         f"{[r.get('config') for r in fleet_rows]}")
    summary = gate_summary(results)
    if not summary["ok"]:
        raise SystemExit(f"fleet perf gate failed: {results}")
    return n, len(per_class), summary


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-fleet-smoke"
    os.makedirs(work, exist_ok=True)
    router_trace = os.path.join(work, "router.jsonl")
    replica_traces = [os.path.join(work, f"router.replica{i}.jsonl")
                      for i in range(REPLICAS)]
    client_trace = os.path.join(work, "client.jsonl")
    for p in [router_trace, client_trace, *replica_traces]:
        if os.path.exists(p):
            os.remove(p)
    telemetry.configure(client_trace)
    telemetry.current().manifest(dict(role="fleet-smoke-client"))

    log_path = os.path.join(work, "router.log")
    # jaxlint: disable-next-line=raw-write — live Popen log handle
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            _router_cmd(work), env=_router_env(work, router_trace),
            cwd=ROOT, stdout=log, stderr=subprocess.STDOUT)
    try:
        ready = _wait_ready(os.path.join(work, "router.json"), proc,
                            log_path)
        port = ready["port"]
        _log(f"router ready on port {port} with {ready['replicas']} "
             f"replicas (kill@replica=1 armed, metrics port "
             f"{ready.get('metrics_port')})")

        replies, sleeps = _flood(port, ready)
        _log(f"flood: {len(replies)} concurrent episode.run all "
             f"answered (no hangs), {len(sleeps)} retry backoffs")
        _check_episodes(replies, "flood")

        stats = _wait_replica_back(port)
        _log(f"killed replica warm-restarted and rejoined: "
             f"{stats['router']['replica_state']}")

        post = _post_restart_flood(port, sleeps)
        _check_episodes(post, "post-restart")
        stats = _stats(port)
        n_fleet = _check_fleet_merge(stats)
        _log(f"fleet latency merge exact over {n_fleet} episode.run "
             f"observations (bucket-sum == merged-by-hand)")

        with ServeClient("127.0.0.1", port) as c:
            r = c.request("drain")
            assert r.get("ok") and r.get("draining"), r
        rc = proc.wait(timeout=300.0)
        if rc != 0:
            tail = open(log_path).read()[-4000:]
            raise SystemExit(f"router exited rc={rc} after drain\n{tail}")
        _log("drain: router and both replicas exited cleanly")
    except BaseException:
        if proc.poll() is None:
            proc.kill()
        raise

    n_requeues = _check_route_trail(router_trace, stats)
    n_sheds = _check_sheds(replica_traces, stats, sleeps)
    _log(f"failover accounting: {n_requeues} requeues, {n_sheds} "
         f"in-band sheds (router stats {stats['router']})")
    _check_reports(replica_traces)
    n_alerts = _check_alerts(replica_traces, stats)
    reasons = _check_blackbox(work)
    fleet_p99 = _check_fleet_report(router_trace)
    _log(f"health plane: {n_alerts} v14 alerts fired, blackbox dumps "
         f"{reasons}, fleet p99 {fleet_p99}")
    telemetry.configure(None)  # close the client sink before reading
    merged = _merge_streams(
        work, [router_trace, *replica_traces, client_trace])
    _validate_stream(merged)
    paired, total = _check_stitch(
        [router_trace, *replica_traces, client_trace])
    _log(f"trace_stitch: {paired}/{total} traces carry the router hop")
    n_rows, n_class, summary = _bank_and_gate(
        work, [*replica_traces, router_trace])
    print(f"fleet-smoke: PASS ({N_SEEDED + N_SEEDLESS + len(post)} "
          f"bit-identical episodes through a replica kill; {n_rows} "
          f"ledger rows banked incl. {n_class} per-class serve_p99_s "
          f"+ fleet_p99_s; {n_alerts} alerts; gate {summary})")


if __name__ == "__main__":
    main()
