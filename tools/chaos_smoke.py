"""Randomized chaos campaign (`make chaos-smoke`).

Where fleet_smoke pins ONE fault script, this campaign derives every
fault from a seed: `cpr_tpu.integrity.ChaosSchedule` composes
randomized sequences of kills, cooperative slowdowns, and artifact
corruption (bit flip / truncation / JSON garbling), and the same seed
replays the exact same campaign — a failure here is a repro command,
not a flake.  Per seed (default two distinct seeds):

  1  schedule replay: constructing the schedule twice from the seed
     must yield the identical description (logged, so the repro is in
     the artifact);
  2  fleet leg: router + 2 replicas launched with the schedule's
     randomized replica fault spec (kill + optional slowdown,
     randomized target) under a 16-client `episode.run` flood — zero
     client hangs, every reply bit-identical to an in-process
     `env.rollout` of its seed, the killed replica warm-restarts, and
     the fleet drains cleanly;
  3  solve leg, CONCURRENT with the flood: a chunked VI solve whose
     n-th checkpoint write is damaged (randomized action) and whose
     next chunk is killed.  Resume finds the corrupt checkpoint,
     quarantines it (typed v16 `integrity` event), falls back to a
     cold start, and must land byte-identical to an uninterrupted
     reference solve;
  4  cache leg: the mdp-grid solve cache entry is damaged by the
     schedule's action on its first write; the next call must treat it
     as a miss (quarantine + recompute, never a crash), and the call
     after that must be a verified hit with bit-equal revenue;
  5  accounting: every injected artifact corruption is matched 1:1 by
     a typed `integrity` event on the same path — no silent damage, no
     phantom reports — and the merged client+router+replica+chaos
     stream validates via `trace_summary --validate --expect
     route,serve,request,integrity`;
  6  ledger leg: the fleet traces bank into a perf ledger,
     `perf_report --gate` runs clean, a hand-tampered row is appended
     (plausible content, stale content hash), and the gate verdicts
     must be unchanged — the corrupt row is skipped with a typed
     `integrity` event instead of poisoning a baseline.

Usage: python tools/chaos_smoke.py [workdir [seed ...]]
       (defaults: /tmp/cpr-chaos-smoke, seeds 11 and 23)
"""

import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import fleet_smoke  # noqa: E402  (reuses its router/flood/trace helpers)
from cpr_tpu import resilience, telemetry  # noqa: E402
from cpr_tpu.integrity import (  # noqa: E402
    ARTIFACT_ACTIONS, ChaosSchedule, quarantine_dir)
from cpr_tpu.serve.protocol import ServeClient  # noqa: E402

SEEDS = (11, 23)
N_FLOOD = 16
PERF_REPORT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "perf_report.py")


def _log(msg):
    print(f"chaos-smoke: {msg}", file=sys.stderr)


# -- solve leg: kill + corrupt + resume --------------------------------------


def _contraction_step(value, prog, steps):
    """chunk_step contract stand-in: `steps` Jacobi sweeps of the map
    v <- (v + 1) / 2 (fixpoint 1) — cheap, deterministic, and chunked
    exactly like a real VI solve, so the checkpoint/resume seam under
    test is the production one."""
    import jax.numpy as jnp

    deltas = []
    v = jnp.asarray(value)
    for _ in range(steps):
        nv = (v + 1.0) / 2.0
        deltas.append(jnp.max(jnp.abs(nv - v)))
        v = nv
    return v, prog, jnp.zeros_like(v, jnp.int32), jnp.stack(deltas)


def _run_vi(checkpoint_path=None):
    from cpr_tpu.mdp.explicit import run_chunk_driver

    return run_chunk_driver(_contraction_step, 8, np.float32, 1e-4, 64,
                            chunk=4, checkpoint_path=checkpoint_path)


def _solve_leg(seed_dir, sched):
    """Damage checkpoint write k, kill chunk k+1, resume: the corrupt
    checkpoint must quarantine and the cold-started resume must equal
    the uninterrupted reference byte for byte."""
    ref_value, _, _, _, ref_it, ref_resid = _run_vi()
    ck = os.path.join(seed_dir, "vi-ck.npz")
    spec = sched.solve_specs()
    os.environ[resilience.FAULT_ENV_VAR] = spec
    try:
        try:
            _run_vi(ck)
        except resilience.InjectedKill:
            pass
        else:
            raise SystemExit(f"solve leg: armed kill in {spec!r} never "
                             f"fired")
    finally:
        os.environ.pop(resilience.FAULT_ENV_VAR, None)
    if not os.path.exists(ck):
        raise SystemExit("solve leg: no checkpoint landed before the "
                         "kill")
    value, _, _, _, it, resid = _run_vi(ck)
    if it != ref_it or not np.array_equal(np.asarray(value),
                                          np.asarray(ref_value)) \
            or not np.array_equal(resid, ref_resid):
        raise SystemExit(
            f"solve leg: resume past the corrupted checkpoint is NOT "
            f"bit-identical to the uninterrupted solve "
            f"(it {it} vs {ref_it})")
    qdir = quarantine_dir(ck)
    if not (os.path.isdir(qdir) and os.listdir(qdir)):
        raise SystemExit(f"solve leg: damaged checkpoint was not "
                         f"quarantined under {qdir}")
    return spec


# -- cache leg: corruption is a miss, never a crash --------------------------


def _cache_leg(seed_dir, sched):
    from cpr_tpu.mdp.grid import solve_grid_cached

    os.environ["CPR_MDP_CACHE"] = os.path.join(seed_dir, "mdp-cache")
    kw = dict(cutoff=4, alphas=(0.3,), gammas=(0.5,), horizon=20,
              stop_delta=1e-4)
    action = sched.cache_action()
    os.environ[resilience.FAULT_ENV_VAR] = f"{action}@cache=1"
    try:
        first = solve_grid_cached("fc16", **kw)  # miss; write damaged
    finally:
        os.environ.pop(resilience.FAULT_ENV_VAR, None)
    if first["cached"] is not False:
        raise SystemExit("cache leg: cold call claimed a cache hit")
    second = solve_grid_cached("fc16", **kw)
    if second["cached"] is not False:
        raise SystemExit(f"cache leg: {action}-damaged entry was "
                         f"served as a hit instead of regenerated")
    third = solve_grid_cached("fc16", **kw)
    if not (third["cached"] is True
            and third.get("integrity") == "verified"
            and third["revenue"] == second["revenue"]
            and second["revenue"] == first["revenue"]):
        raise SystemExit(f"cache leg: post-recovery hit is not a "
                         f"verified bit-equal entry: {third}")
    return action


# -- corruption accounting ---------------------------------------------------


def _match_corruptions(trace, label):
    """Every injected artifact damage must produce exactly one typed
    `integrity` event on the same path, and every `integrity` event
    must trace back to an injection — no silent damage, no phantom
    reports."""
    injected, reported = [], []
    with open(trace) as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") != "event":
                continue
            if e.get("name") == "fault_injected" and \
                    e.get("spec", "").split("@")[0] in ARTIFACT_ACTIONS:
                injected.append(e["artifact"])
            elif e.get("name") == "integrity":
                reported.append(e["artifact"])
    if sorted(injected) != sorted(reported):
        raise SystemExit(
            f"{label}: injected corruptions and integrity events do "
            f"not match 1:1 — injected {sorted(injected)}, reported "
            f"{sorted(reported)}")
    if not injected:
        raise SystemExit(f"{label}: campaign injected no artifact "
                         f"corruption at all")
    return len(injected)


# -- ledger leg: a tampered row cannot poison the gate -----------------------


def _gate_lines(ledger_path, tele=None):
    env = dict(os.environ)
    env.pop(resilience.FAULT_ENV_VAR, None)
    if tele:
        env["CPR_TELEMETRY"] = tele
    else:
        env.pop("CPR_TELEMETRY", None)
    r = subprocess.run(
        [sys.executable, PERF_REPORT, ledger_path, "--gate"],
        capture_output=True, text=True, env=env, cwd=ROOT)
    # rc=1 is a legitimate FAIL verdict (a chaos-killed replica banks
    # zero-valued drain rows); only a crash is a smoke failure.  What
    # the leg asserts is that the verdicts — rc included — are
    # IDENTICAL before and after the tamper.
    if r.returncode not in (0, 1):
        raise SystemExit(f"perf_report --gate crashed rc={r.returncode}"
                         f"\n{r.stdout}{r.stderr}")
    lines = [ln for ln in r.stdout.splitlines()
             if ln.startswith("gate:")]
    if not lines:
        raise SystemExit(f"perf_report --gate produced no gate "
                         f"verdicts\n{r.stdout}")
    return [f"rc={r.returncode}"] + lines


def _ledger_leg(seed_dir, traces):
    from cpr_tpu.perf.ledger import Ledger

    ledger_path = os.path.join(seed_dir, "perf_ledger.jsonl")
    n = sum(Ledger(ledger_path).ingest_trace(p) for p in traces)
    if not n:
        raise SystemExit("ledger leg: fleet traces banked no rows")
    clean = _gate_lines(ledger_path)

    # hand-tamper: append a copy of a banked row with an inflated
    # value but the ORIGINAL row_id — plausible JSON whose content
    # hash no longer matches.  If records() trusted it, the gate
    # verdicts would shift; the integrity plane must skip it instead.
    with open(ledger_path) as f:
        rows = [json.loads(ln) for ln in f if ln.strip()]
    mutant = dict(rows[-1])
    mutant["value"] = float(mutant.get("value", 1.0) or 1.0) * 1000 + 1
    with open(ledger_path, "a") as f:
        f.write(json.dumps(mutant, sort_keys=True) + "\n")

    tele = os.path.join(seed_dir, "ledger-tele.jsonl")
    corrupted = _gate_lines(ledger_path, tele=tele)
    if corrupted != clean:
        raise SystemExit(
            f"ledger leg: tampered row CHANGED the gate verdicts:\n"
            f"clean:     {clean}\ncorrupted: {corrupted}")
    events = [e for e in fleet_smoke._events(tele, "integrity")
              if e.get("artifact_kind") == "ledger_row"
              and e.get("reason") == "checksum"]
    if not events:
        raise SystemExit("ledger leg: skipped tampered row emitted no "
                         "typed integrity event")
    return len(clean)


# -- the per-seed campaign ---------------------------------------------------


def _campaign(seed, work):
    seed_dir = os.path.join(work, f"seed{seed}")
    os.makedirs(seed_dir, exist_ok=True)
    sched = ChaosSchedule(seed, rounds=1,
                          replicas=fleet_smoke.REPLICAS)
    replay = ChaosSchedule(seed, rounds=1,
                           replicas=fleet_smoke.REPLICAS)
    if replay.describe() != sched.describe():
        raise SystemExit(f"seed {seed}: schedule is not replayable "
                         f"from its seed")
    _log(f"seed {seed}: schedule {json.dumps(sched.describe())}")

    trace = os.path.join(seed_dir, "chaos.jsonl")
    router_trace = os.path.join(seed_dir, "router.jsonl")
    replica_traces = [
        os.path.join(seed_dir, f"router.replica{i}.jsonl")
        for i in range(fleet_smoke.REPLICAS)]
    for p in [trace, router_trace, *replica_traces]:
        if os.path.exists(p):
            os.remove(p)
    telemetry.configure(trace)
    telemetry.current().manifest(
        dict(role="chaos-smoke", schedule=sched.describe()))

    fleet_spec = sched.fleet_specs()[0]
    env = fleet_smoke._router_env(seed_dir, router_trace)
    env["CPR_FAULT_INJECT"] = fleet_spec
    log_path = os.path.join(seed_dir, "router.log")
    # jaxlint: disable-next-line=raw-write — live Popen log handle
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            fleet_smoke._router_cmd(seed_dir), env=env,
            cwd=fleet_smoke.ROOT, stdout=log,
            stderr=subprocess.STDOUT)
    try:
        ready = fleet_smoke._wait_ready(
            os.path.join(seed_dir, "router.json"), proc, log_path)
        port = ready["port"]
        _log(f"seed {seed}: fleet up on port {port} with "
             f"{fleet_spec!r} armed")

        sleeps, lock = [], threading.Lock()
        base = 40000 + 1000 * seed
        with ThreadPoolExecutor(max_workers=N_FLOOD) as pool:
            jobs = [pool.submit(fleet_smoke._flood_worker, port,
                                base + i, sleeps, lock)
                    for i in range(N_FLOOD)]
            # the solve leg runs WHILE the flood is in flight — the
            # in-process fault env never reaches the router subprocess
            solve_spec = _solve_leg(seed_dir, sched)
            _log(f"seed {seed}: solve leg {solve_spec!r} — corrupt "
                 f"checkpoint quarantined, resume bit-identical")
            deadline = time.time() + fleet_smoke.FLOOD_TIMEOUT_S
            replies = [
                j.result(timeout=max(1.0, deadline - time.time()))
                for j in jobs]  # a timeout here IS a client hang
        fleet_smoke._check_episodes(replies, f"seed {seed} flood")

        fleet_smoke._wait_replica_back(port)
        _log(f"seed {seed}: killed replica warm-restarted and "
             f"rejoined")
        with ServeClient("127.0.0.1", port) as c:
            r = c.request("drain")
            assert r.get("ok") and r.get("draining"), r
        rc = proc.wait(timeout=300.0)
        if rc != 0:
            tail = open(log_path).read()[-4000:]
            raise SystemExit(f"router exited rc={rc} after drain\n"
                             f"{tail}")
    except BaseException:
        if proc.poll() is None:
            proc.kill()
        raise

    cache_action = _cache_leg(seed_dir, sched)
    _log(f"seed {seed}: cache leg {cache_action!r} — corrupt entry "
         f"regenerated, clean hit verified")

    telemetry.configure(None)  # close the sink before reading it
    n_corruptions = _match_corruptions(trace, f"seed {seed}")
    merged = fleet_smoke._merge_streams(
        seed_dir, [router_trace, *replica_traces, trace])
    fleet_smoke._validate_stream(
        merged, expect="route,serve,request,integrity")
    n_gates = _ledger_leg(seed_dir,
                          [*replica_traces, router_trace])
    _log(f"seed {seed}: {n_corruptions} injected corruptions matched "
         f"1:1 by integrity events; {n_gates} gate verdicts immune to "
         f"the tampered ledger row")
    return len(replies), n_corruptions, n_gates


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-chaos-smoke"
    seeds = ([int(s) for s in sys.argv[2:]] if len(sys.argv) > 2
             else list(SEEDS))
    if len(seeds) < 2:
        raise SystemExit("chaos-smoke needs >= 2 distinct seeds")
    os.makedirs(work, exist_ok=True)
    totals = [_campaign(seed, work) for seed in seeds]
    n_eps = sum(t[0] for t in totals)
    n_corr = sum(t[1] for t in totals)
    print(f"chaos-smoke: PASS (seeds {seeds}: {n_eps} bit-identical "
          f"episodes through randomized replica faults, {n_corr} "
          f"injected corruptions quarantined + matched 1:1, "
          f"kill+corrupt VI resumes bit-identical, corrupt cache "
          f"entries regenerated, tampered ledger rows gate-inert)")


if __name__ == "__main__":
    main()
