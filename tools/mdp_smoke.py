"""Grid-batched MDP smoke (`make mdp-smoke`).

Proves the parametric-compile + grid-VI pipeline (docs/MDP.md)
end-to-end on the CPU CI host — solve children run under forced
1-device and 4-device XLA CPU meshes, so the grid-axis sharding seam
is exercised with no accelerator:

  1  per device count, a solve child parametrically compiles fc16 +
     aft20 (fork length 20), proves revalue parity against fresh
     compiles at probe points, and solves the same 16-point
     (alpha, gamma) grid per protocol as ONE vmapped (and, at 4
     devices, grid-axis-sharded) VI program;
  2  the 1-device child additionally runs the telemetry-spanned A/B:
     the serial battery loop (fresh compile + ptmdp + solo chunked
     solve per point) vs [one parametric compile + one grid solve] —
     the grid side must win >= 3x wall-clock across the two
     protocols — and spot-checks grid fixpoints bit-identical to solo
     solves of the same revalued tensors at the grid corners;
  3  device-count parity: per-point value/progress/policy planes and
     convergence sweep counts must be BIT-IDENTICAL between the
     1-device and 4-device grid solves — same program, partitioned;
  4  a supervised `python -m cpr_tpu.serve.server` answers
     `mdp.solve_grid` twice: the first solve banks an `mdp_solve`
     event, the repeat must come back `cached` with identical revenue
     (the content-fingerprint solve cache);
  5  every trace passes `trace_summary --validate --expect mdp_solve`
     (serve trace: `--expect serve`), and all traces ingest into one
     perf ledger: `mdp_grid_points_per_sec` rows must land at BOTH
     cfg_devices=1 and cfg_devices=4 and every banked row (including
     the lower-is-better `mdp_grid_point_solve_s`) must clear the
     regression gate.

Usage: python tools/mdp_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from cpr_tpu import supervisor  # noqa: E402
from cpr_tpu.perf.gate import gate_row, gate_summary  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402
from cpr_tpu.serve.protocol import ServeClient  # noqa: E402

DEVICES = 4                 # the forced virtual CPU mesh span
MFL = 20                    # battery fork-length for fc16/aft20
HORIZON = 50
N_ALPHAS = 8                # x len(GAMMAS) = 16 grid points/protocol
GAMMAS = (0.25, 0.75)
AB_MIN_SPEEDUP = 3.0
READY_TIMEOUT_S = 300.0
WALL_S = 900.0


def _log(msg):
    print(f"mdp-smoke: {msg}", file=sys.stderr)


def _child_env(workdir, trace, extra=None, devices=1):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=f"--xla_force_host_platform_device_count="
                         f"{devices}",
               CPR_TELEMETRY=trace,
               CPR_TPU_CACHE=os.path.join(workdir, "cache"))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def _validate_stream(trace, expect):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, trace, "--validate", "--expect", expect],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


# one solve child per device count: parametric compile + parity + grid
# solve, exact outputs dumped as JSON for the parent's cross-device
# bit-identity check; the 1-device child also runs the spanned A/B and
# the solo-fixpoint spot check
_SOLVE_CHILD = textwrap.dedent("""\
    import json, os

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from cpr_tpu import telemetry
    from cpr_tpu.mdp import Compiler, ptmdp
    from cpr_tpu.mdp.explicit import MDP
    from cpr_tpu.mdp.grid import (check_revalue_parity, compile_protocol,
                                  grid_value_iteration, param_ptmdp)
    from cpr_tpu.mdp.models import Aft20BitcoinSM, Fc16BitcoinSM
    from cpr_tpu.telemetry import now

    devices = int(os.environ["CPR_SMOKE_DEVICES"])
    mfl = int(os.environ["CPR_SMOKE_MFL"])
    horizon = int(os.environ["CPR_SMOKE_HORIZON"])
    n_alphas = int(os.environ["CPR_SMOKE_N_ALPHAS"])
    gammas = tuple(float(g) for g in
                   os.environ["CPR_SMOKE_GAMMAS"].split(","))
    run_ab = os.environ.get("CPR_SMOKE_AB") == "1"
    alphas = [round(float(a), 6)
              for a in np.linspace(0.15, 0.45, n_alphas)]

    mesh = None
    if devices > 1:
        from cpr_tpu.parallel import default_mesh
        devs = jax.devices()
        assert len(devs) >= devices, (len(devs), devices)
        mesh = default_mesh(devices=devs[:devices])

    tele = telemetry.current()
    tele.manifest(dict(role="mdp-smoke-solve", devices=devices,
                       mfl=mfl, horizon=horizon))

    MODELS = {
        "fc16": Fc16BitcoinSM,
        "aft20": Aft20BitcoinSM,
    }

    def solo_tensor(pt, a, g):
        # a solo tensor over the SAME revalued probability column the
        # grid solved (fresh compiles differ by float association)
        src, act, dst, _, reward, progress = pt.mdp.arrays()
        m = MDP(n_states=pt.mdp.n_states, n_actions=pt.mdp.n_actions,
                start=dict(pt.mdp.start), src=src, act=act, dst=dst,
                prob=pt.revalue(a, g), reward=reward, progress=progress)
        return m.tensor()

    payload = dict(devices=devices, grids={}, ab={})
    for proto, cls in MODELS.items():
        pm = compile_protocol(proto, cutoff=mfl)
        n = check_revalue_parity(
            pm, lambda a, g, cls=cls: cls(alpha=a, gamma=g,
                                          maximum_fork_length=mfl),
            [(0.2, 0.3), (0.33, 0.5), (0.45, 0.9)])
        print(f"{proto}: revalue parity ok at {n} probe points")
        pt = param_ptmdp(pm, horizon=horizon)
        with tele.span(f"mdp_ab:grid:{proto}"):
            t0 = now()
            vi = grid_value_iteration(pt, alphas, gammas,
                                      stop_delta=1e-6, mesh=mesh,
                                      protocol=proto, cutoff=mfl)
            grid_s = now() - t0
        assert bool(vi["grid_converged"].all()), proto
        payload["grids"][proto] = dict(
            value=vi["grid_value"].tolist(),
            progress=vi["grid_progress"].tolist(),
            policy=vi["grid_policy"].tolist(),
            conv_iter=vi["grid_iter"].tolist(),
            revenue=vi["grid_revenue"].tolist(),
            sweeps=int(vi["vi_iter"]),
        )
        if not run_ab:
            continue
        # grid corners: solo chunked solves of the same revalued
        # tensors must reproduce the grid fixpoints bit-for-bit
        pts = list(vi["grid_points"])
        for gi in (0, len(gammas) - 1, len(pts) - len(gammas),
                   len(pts) - 1):
            a, g = pts[gi]
            solo = solo_tensor(pt, a, g).value_iteration(
                impl="chunked", stop_delta=1e-6)
            for plane, key in ((vi["grid_value"][gi], "vi_value"),
                               (vi["grid_progress"][gi], "vi_progress"),
                               (vi["grid_policy"][gi], "vi_policy")):
                assert np.array_equal(plane, solo[key]), (proto, a, g,
                                                         key)
            assert int(vi["grid_iter"][gi]) == int(solo["vi_iter"])
        print(f"{proto}: grid corners bit-identical to solo solves")
        # the serial battery loop this PR replaces: fresh compile +
        # ptmdp + solo chunked solve per grid point
        with tele.span(f"mdp_ab:serial:{proto}"):
            t0 = now()
            for a, g in pts:
                m = ptmdp(Compiler(cls(alpha=a, gamma=g,
                                       maximum_fork_length=mfl)).mdp(),
                          horizon=horizon)
                m.tensor().value_iteration(impl="chunked",
                                           stop_delta=1e-6)
            serial_s = now() - t0
        payload["ab"][proto] = dict(points=len(pts), serial_s=serial_s,
                                    grid_s=grid_s,
                                    speedup=serial_s / grid_s)
        print(f"{proto}: A/B serial {serial_s:.2f}s vs grid "
              f"{grid_s:.2f}s -> {serial_s / grid_s:.2f}x")

    if run_ab:
        tot_serial = sum(r["serial_s"] for r in payload["ab"].values())
        tot_grid = sum(r["grid_s"] for r in payload["ab"].values())
        payload["ab"]["combined_speedup"] = tot_serial / tot_grid
        min_speedup = float(os.environ["CPR_SMOKE_MIN_SPEEDUP"])
        assert tot_serial / tot_grid >= min_speedup, (
            f"grid solve only {tot_serial / tot_grid:.2f}x faster than "
            f"the serial loop, need >= {min_speedup}x")

    with open(os.environ["CPR_SMOKE_OUT"], "w") as f:
        json.dump(payload, f, sort_keys=True)
    print("mdp solve child ok:", devices, "device(s)")
""")


def _solve_run(work, devices, run_ab):
    trace = os.path.join(work, f"solve_d{devices}.jsonl")
    out_path = os.path.join(work, f"solve_d{devices}.json")
    for p in (trace, out_path):
        if os.path.exists(p):
            os.remove(p)
    env = _child_env(work, trace, devices=devices, extra={
        "CPR_SMOKE_DEVICES": str(devices),
        "CPR_SMOKE_MFL": str(MFL),
        "CPR_SMOKE_HORIZON": str(HORIZON),
        "CPR_SMOKE_N_ALPHAS": str(N_ALPHAS),
        "CPR_SMOKE_GAMMAS": ",".join(str(g) for g in GAMMAS),
        "CPR_SMOKE_AB": "1" if run_ab else "0",
        "CPR_SMOKE_MIN_SPEEDUP": str(AB_MIN_SPEEDUP),
        "CPR_SMOKE_OUT": out_path,
    })
    r = subprocess.run([sys.executable, "-c", _SOLVE_CHILD], env=env,
                       cwd=ROOT, capture_output=True, text=True,
                       timeout=WALL_S)
    sys.stderr.write(r.stdout)
    if r.returncode != 0:
        sys.stderr.write(r.stderr)
        raise SystemExit(f"solve child (devices={devices}) failed "
                         f"rc={r.returncode}")
    _validate_stream(trace, "mdp_solve")
    with open(out_path) as f:
        payload = json.load(f)
    _log(f"solve child devices={devices}: fc16+aft20, "
         f"{N_ALPHAS * len(GAMMAS)} grid points each")
    return payload, trace


def _wait_ready(path, proc):
    deadline = time.time() + READY_TIMEOUT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            raise SystemExit(f"server child exited rc={proc.returncode} "
                             f"before becoming ready")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            time.sleep(0.25)
    raise SystemExit(f"server not ready within {READY_TIMEOUT_S:.0f}s")


def _serve_run(work):
    """Supervised serve child answering mdp.solve_grid: the repeat
    query must hit the content-fingerprint solve cache."""
    trace = os.path.join(work, "serve_mdp.jsonl")
    if os.path.exists(trace):
        os.remove(trace)
    cmd = [sys.executable, "-m", "cpr_tpu.serve.server",
           "--protocol", "nakamoto", "--max-steps", "64",
           "--lanes", "2", "--burst", "32", "--devices", "1",
           "--heartbeat-s", "0.5",
           "--ready-file", os.path.join(work, "ready_mdp.json")]
    started = threading.Event()
    box = {}

    def on_start(proc):
        box["proc"] = proc
        started.set()

    def supervise():
        box["attempt"] = supervisor.run_child(
            cmd, wall_timeout_s=WALL_S, quiet_s=60.0, heartbeat_s=1.0,
            env=_child_env(work, trace), cwd=ROOT, on_start=on_start)

    child = threading.Thread(target=supervise)
    child.start()
    try:
        if not started.wait(30.0):
            raise SystemExit("run_child never spawned the server")
        ready = _wait_ready(os.path.join(work, "ready_mdp.json"),
                            box["proc"])
        port = ready["port"]
        _log(f"serve child ready on port {port}")
        query = dict(protocol="fc16", cutoff=6, alphas=[0.25, 0.4],
                     gammas=[0.3, 0.8], horizon=30)
        with ServeClient("127.0.0.1", port) as c:
            r1 = c.request("mdp.solve_grid", **query)
            assert r1.get("ok"), f"mdp.solve_grid: {r1}"
            assert r1["cached"] is False, r1
            r2 = c.request("mdp.solve_grid", **query)
            assert r2.get("ok") and r2["cached"] is True, r2
        if r1["revenue"] != r2["revenue"]:
            raise SystemExit("cached mdp.solve_grid replay changed the "
                             "revenue table")
        if r1["fingerprint"] != r2["fingerprint"]:
            raise SystemExit("solve-cache fingerprint drifted between "
                             "identical queries")
        box["proc"].send_signal(signal.SIGTERM)
    except BaseException:
        proc = box.get("proc")
        if proc is not None and proc.poll() is None:
            proc.kill()
        raise
    child.join(120.0)
    if child.is_alive():
        raise SystemExit("server child did not drain within 120s")
    attempt = box["attempt"]
    if attempt.status != "ok" or attempt.rc != 0:
        raise SystemExit(f"serve child did not exit cleanly "
                         f"(status={attempt.status} rc={attempt.rc})")
    _validate_stream(trace, "serve,mdp_solve")
    _log(f"serve mdp.solve_grid: solved then cache-hit, "
         f"{len(r1['revenue'])} points, drained clean")
    return trace


def _bank_and_gate(work, traces):
    """All traces into one ledger; mdp_grid_points_per_sec must land
    at both device counts and every banked row must clear the gate."""
    ledger = Ledger(os.path.join(work, "perf_ledger.jsonl"))
    n = sum(ledger.ingest_trace(t) for t in traces)
    records = ledger.records()
    pps = [r for r in records
           if r.get("metric") == "mdp_grid_points_per_sec"]
    got = {r.get("config", {}).get("cfg_devices") for r in pps}
    if not {1, DEVICES} <= got:
        raise SystemExit(f"mdp_grid_points_per_sec banked at device "
                         f"counts {sorted(got)}, need both 1 and "
                         f"{DEVICES}")
    lat = [r for r in records
           if r.get("metric") == "mdp_grid_point_solve_s"]
    if not lat:
        raise SystemExit("no mdp_grid_point_solve_s rows banked")
    results = [gate_row(r, records) for r in records]
    summary = gate_summary(results)
    if not summary["ok"]:
        bad = [res for res in results if res["verdict"] == "fail"]
        raise SystemExit(f"mdp perf gate failed: {bad}")
    return n, summary


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-mdp-smoke"
    os.makedirs(work, exist_ok=True)

    out_1, trace_1 = _solve_run(work, 1, run_ab=True)
    out_n, trace_n = _solve_run(work, DEVICES, run_ab=False)
    if out_1["grids"] != out_n["grids"]:
        raise SystemExit(f"grid solves NOT bit-identical between "
                         f"1-device and {DEVICES}-device runs")
    _log(f"grid fixpoints bit-identical at 1 vs {DEVICES} devices "
         f"(fc16 + aft20, {N_ALPHAS * len(GAMMAS)} points each)")

    trace_s = _serve_run(work)

    n, summary = _bank_and_gate(work, [trace_1, trace_n, trace_s])
    ab = out_1["ab"]
    print(f"mdp-smoke: PASS (parametric compile + grid VI bit-identical "
          f"at 1 vs {DEVICES} devices; A/B "
          f"{ab['combined_speedup']:.1f}x >= {AB_MIN_SPEEDUP:.0f}x vs "
          f"the serial loop [fc16 {ab['fc16']['speedup']:.1f}x, aft20 "
          f"{ab['aft20']['speedup']:.1f}x]; serve mdp.solve_grid "
          f"cache-hit round-trip; banked {n} ledger rows incl. "
          f"mdp_grid_points_per_sec at devices 1 and {DEVICES}; "
          f"gate {summary})")


if __name__ == "__main__":
    main()
