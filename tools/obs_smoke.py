"""Observability smoke (`make obs-smoke`): the v15 attribution plane
proved end-to-end on CPU, twice through the real serving stack.

  1  run a supervised `cpr_tpu.serve.server` child (A), drive a small
     seeded policy load, scrape the in-band metrics mid-run and assert
     the live memory watermark gauges are exposed, SIGTERM-drain it,
     and assert the drain report banks a `memory` block;
  2  run the identical child again (B) with a one-shot injected stall
     (`CPR_FAULT_INJECT=slow@replica=0`) landing inside the
     `serve_burst` span — a synthetic regression with a known culprit;
  3  both traces must pass `trace_summary --validate --expect
     serve,device_metrics,memory`, then both runs are archived
     (content-addressed, distinct run ids) under the workdir;
  4  `trace_diff` over the two *archived run ids* must rank the
     injected `serve_burst` span as the #1 culprit by self-time delta;
  5  both traces bank into a perf ledger: the B `serve_p99_s` row must
     FAIL its gate against the A baseline with `run`/`baseline_runs`
     naming the archived pair, the lower-is-better `serve_peak_bytes`
     watermark row must gate clean, and `perf_report --gate
     --attribute` run as a subprocess must chase the FAIL through the
     archive and print an attribution table naming `serve_burst`.

A PASS means the whole chain — watermark sampling, schema-v15 events,
run archive, span diff, gate provenance, report attribution — holds
together on a real child process, not just in unit tests.

Usage: python tools/obs_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from cpr_tpu import supervisor, telemetry  # noqa: E402
from cpr_tpu.perf import archive  # noqa: E402
from cpr_tpu.perf.gate import gate_row  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402
from cpr_tpu.serve.protocol import ServeClient  # noqa: E402

MAX_STEPS = 256
LANES = 8
BURST = 256
EPISODES = 12
READY_TIMEOUT_S = 300.0
WALL_S = 300.0
SLOW_S = 0.75  # resilience._DEFAULT_SLOW_S — the injected regression


def _log(msg):
    print(f"obs-smoke: {msg}", file=sys.stderr)


def _child_cmd(work, name):
    # --replica-index arms the per-replica fault site in run B; run A
    # passes it too so the two configs fingerprint identically and the
    # ledger gate judges B against A rather than skipping on drift
    return [sys.executable, "-m", "cpr_tpu.serve.server",
            "--protocol", "nakamoto", "--max-steps", str(MAX_STEPS),
            "--lanes", str(LANES), "--burst", str(BURST),
            "--heartbeat-s", "0.5", "--replica-index", "0",
            "--ready-file", os.path.join(work, f"{name}-ready.json")]


def _child_env(work, trace, inject):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CPR_TELEMETRY=trace, CPR_DEVICE_METRICS="1",
               CPR_TPU_CACHE=os.path.join(work, "cache"),
               # the SIGTERM drain dumps the flight recorder; keep the
               # dumps inside the smoke workdir, not the repo's runs/
               CPR_BLACKBOX_DIR=os.path.join(work, "blackbox"))
    env.pop("CPR_FAULT_INJECT", None)
    if inject:
        env["CPR_FAULT_INJECT"] = "slow@replica=0"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _wait_ready(path, proc):
    deadline = time.time() + READY_TIMEOUT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            raise SystemExit(f"server child exited rc={proc.returncode} "
                             f"before becoming ready")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            time.sleep(0.25)
    raise SystemExit(f"server not ready within {READY_TIMEOUT_S:.0f}s")


def _episode(port, seed):
    with ServeClient("127.0.0.1", port) as c:
        r = c.request("episode.run", policy="honest", seed=seed)
        assert r.get("ok"), f"episode.run(seed={seed}): {r}"
        return r


def _load(port):
    with ThreadPoolExecutor(max_workers=4) as pool:
        jobs = [pool.submit(_episode, port, 100 + i)
                for i in range(EPISODES)]
        for j in jobs:
            j.result()
    return EPISODES


def _scrape_memory_gauges(port):
    """Mid-run in-band scrape: the watermark gauges must be live in
    the registry while the server is serving, not only at drain."""
    with ServeClient("127.0.0.1", port) as c:
        r = c.request("metrics.scrape")
        assert r.get("ok"), f"metrics.scrape: {r}"
    gauges = (r.get("metrics") or {}).get("gauges") or {}
    missing = [g for g in ("memory_peak_bytes", "memory_in_use_bytes")
               if g not in gauges]
    if missing:
        raise SystemExit(f"mid-run scrape lacks watermark gauges "
                         f"{missing} (have {sorted(gauges)})")
    peak = gauges["memory_peak_bytes"][0]["value"]
    if not peak > 0:
        raise SystemExit(f"memory_peak_bytes gauge not positive: {peak}")
    return peak


def _serve_events(trace, action=None):
    out = []
    with open(trace) as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") == "event" and e.get("name") == "serve" \
                    and (action is None or e.get("action") == action):
                out.append(e)
    return out


def _check_drain_memory(trace):
    reports = _serve_events(trace, "report")
    detail = (reports[-1].get("detail") or {}) if reports else {}
    mem = detail.get("memory") or {}
    if not (isinstance(mem.get("peak_bytes"), (int, float))
            and mem["peak_bytes"] > 0 and mem.get("source")):
        raise SystemExit(f"drain report carries no usable memory "
                         f"watermark: {mem or sorted(detail)}")
    return mem


def _validate_stream(trace):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, trace, "--validate",
         "--expect", "serve,device_metrics,memory"],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


def run_one(work, name, inject):
    """One supervised server lifecycle; returns the trace path."""
    trace = os.path.join(work, f"{name}.jsonl")
    if os.path.exists(trace):
        os.remove(trace)
    # each lifecycle is its own run: run_child stamps the parent's run
    # id into the child env, so without a re-mint both servers would
    # archive under one record and there would be no A/B pair to diff
    rid = telemetry.reset_run_id()
    _log(f"run {name}: minted run id {rid}")
    started = threading.Event()
    box = {}

    def on_start(proc):
        box["proc"] = proc
        started.set()

    def supervise():
        box["attempt"] = supervisor.run_child(
            _child_cmd(work, name), wall_timeout_s=WALL_S, quiet_s=20.0,
            heartbeat_s=1.0, env=_child_env(work, trace, inject),
            cwd=ROOT, on_start=on_start)

    child = threading.Thread(target=supervise)
    child.start()
    try:
        if not started.wait(30.0):
            raise SystemExit("run_child never spawned the server")
        ready = _wait_ready(os.path.join(work, f"{name}-ready.json"),
                            box["proc"])
        port = ready["port"]
        _log(f"run {name}: server ready on port {port}"
             f"{' (slow@replica armed)' if inject else ''}")
        n = _load(port)
        peak = _scrape_memory_gauges(port)
        _log(f"run {name}: {n} episodes served; live watermark "
             f"{peak / 2 ** 20:.1f} MiB in mid-run scrape")
        box["proc"].send_signal(signal.SIGTERM)
    except BaseException:
        proc = box.get("proc")
        if proc is not None and proc.poll() is None:
            proc.kill()
        raise
    child.join(120.0)
    if child.is_alive():
        raise SystemExit(f"run {name}: child did not drain within 120s")
    attempt = box["attempt"]
    if attempt.status != "ok" or attempt.rc != 0:
        raise SystemExit(f"run {name}: child did not exit cleanly "
                         f"(status={attempt.status} rc={attempt.rc})")
    mem = _check_drain_memory(trace)
    _validate_stream(trace)
    _log(f"run {name}: drained; report watermark "
         f"{mem['peak_bytes'] / 2 ** 20:.1f} MiB "
         f"(source {mem['source']}); stream validates with memory "
         f"events")
    return trace


def _check_diff(run_a, run_b, arch):
    import trace_diff

    base_label, cand_label, d = trace_diff.run_diff(run_a, run_b, arch)
    culprits = d["culprits"]
    if not culprits:
        raise SystemExit("trace_diff found no span culprits at all")
    top = culprits[0]
    if top["path"] != "serve_burst":
        raise SystemExit(
            f"trace_diff blamed '{top['path']}' "
            f"(d_self={top['d_self_s']:.3f}s), expected the injected "
            f"serve_burst; top 3: "
            f"{[(c['path'], round(c['d_self_s'], 3)) for c in culprits[:3]]}")
    if top["d_self_s"] < 0.5 * SLOW_S:
        raise SystemExit(
            f"serve_burst self-time delta {top['d_self_s']:.3f}s does "
            f"not account for the injected {SLOW_S}s stall")
    return top


def _check_gates(work, trace_a, trace_b, run_a, run_b):
    ledger = Ledger(os.path.join(work, "perf_ledger.jsonl"))
    n = ledger.ingest_trace(trace_a) + ledger.ingest_trace(trace_b)
    records = ledger.records()

    def rows(metric, run):
        return [r for r in records
                if r.get("metric") == metric and r.get("run") == run]

    p99 = rows("serve_p99_s", run_b)
    if not p99:
        raise SystemExit("no serve_p99_s row banked for run B")
    res = gate_row(p99[-1], records)
    if res["verdict"] != "fail":
        raise SystemExit(
            f"injected stall did not fail the serve_p99_s gate: {res}")
    if res["run"] != run_b or run_a not in res["baseline_runs"]:
        raise SystemExit(
            f"gate verdict lacks archive provenance: run={res['run']} "
            f"baseline_runs={res['baseline_runs']}")

    peak = rows("serve_peak_bytes", run_b)
    if not peak:
        raise SystemExit("no serve_peak_bytes watermark row banked "
                         "for run B")
    mres = gate_row(peak[-1], records)
    if mres["verdict"] not in ("pass", "warn"):
        raise SystemExit(f"serve_peak_bytes watermark gate: {mres}")
    return n, res, mres


def _check_attribution(work, arch):
    """perf_report --gate --attribute as production would run it: the
    FAIL must exit 1 and the report must chase it through the archive
    into a culprit table naming serve_burst."""
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "perf_report.py")
    r = subprocess.run(
        [sys.executable, tool, os.path.join(work, "perf_ledger.jsonl"),
         "--metric", "serve_p99_s", "--gate", "--attribute",
         "--archive", arch],
        capture_output=True, text=True)
    out = r.stdout + r.stderr
    if r.returncode != 1:
        sys.stderr.write(out)
        raise SystemExit(f"perf_report --gate --attribute exited "
                         f"{r.returncode}, expected 1 (gated FAIL)")
    if "attribution: serve_p99_s" not in out:
        sys.stderr.write(out)
        raise SystemExit("perf_report printed no attribution section "
                         "for the failed serve_p99_s gate")
    if "serve_burst" not in out:
        sys.stderr.write(out)
        raise SystemExit("perf_report attribution does not name the "
                         "injected serve_burst span")


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-obs-smoke"
    os.makedirs(work, exist_ok=True)
    arch = os.path.join(work, "archive")

    trace_a = run_one(work, "a", inject=False)
    trace_b = run_one(work, "b", inject=True)

    rec_a = archive.archive_run(paths=[trace_a], root=arch,
                                label="obs-smoke baseline",
                                roles={trace_a: "server"})
    rec_b = archive.archive_run(paths=[trace_b], root=arch,
                                label="obs-smoke slow@replica",
                                roles={trace_b: "server"})
    run_a, run_b = rec_a["run"], rec_b["run"]
    if run_a == run_b:
        raise SystemExit(f"both runs archived under one id ({run_a}) — "
                         f"no A/B pair to diff")
    _log(f"archived baseline {run_a} and candidate {run_b} "
         f"under {arch}")

    top = _check_diff(run_a, run_b, arch)
    _log(f"trace_diff: top culprit {top['path']} "
         f"d_self={top['d_self_s']:+.3f}s "
         f"(share {top['share_of_delta']:.0%})")

    n_banked, p99_res, mem_res = _check_gates(work, trace_a, trace_b,
                                              run_a, run_b)
    _log(f"ledger: {n_banked} rows banked; serve_p99_s gate FAIL with "
         f"provenance run={p99_res['run']} baselines="
         f"{p99_res['baseline_runs']}; serve_peak_bytes gate "
         f"{mem_res['verdict']}")

    _check_attribution(work, arch)
    print(f"obs-smoke: PASS (injected {SLOW_S}s stall attributed to "
          f"serve_burst: diff d_self={top['d_self_s']:+.3f}s; "
          f"serve_p99_s gate FAIL carried archived run pair "
          f"{run_a} -> {run_b}; perf_report --attribute named the "
          f"culprit; watermarks live in scrape + drain report; "
          f"{n_banked} ledger rows banked incl. serve_peak_bytes "
          f"[{mem_res['verdict']}])")


if __name__ == "__main__":
    main()
