"""Summarize (and validate) a cpr_tpu telemetry JSONL stream.

Reads the event file written via `CPR_TELEMETRY=<path>` (or
`cpr_tpu.telemetry.configure`), prints per-span aggregates — calls,
total/mean wall time, share of the total — and a throughput table for
spans carrying counters (env_steps etc.), plus any manifests and
outage/revert events.  The post-mortem half of the telemetry layer:
`bench.py`, the training driver, and the sweeps write the stream; this
reads it back without re-running anything.

`--validate` additionally checks the artifact is schema-complete
(every span event carries the SPAN_KEYS, timestamps are monotonic
non-negative intervals, at least one manifest names its backend) and
exits nonzero otherwise — `make telemetry-smoke` runs a tiny bench and
asserts through this mode.

Usage: python tools/trace_summary.py <telemetry.jsonl> [--validate]
"""

import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from cpr_tpu.telemetry import SPAN_KEYS  # noqa: E402


def read_events(path):
    events, bad = [], []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError as e:
                bad.append(f"line {i}: not JSON ({e})")
    return events, bad


def validate(events, bad):
    """Schema-completeness errors for `--validate` (empty list = ok)."""
    errors = list(bad)
    if not events:
        errors.append("empty event stream")
    for i, e in enumerate(events, 1):
        if not isinstance(e, dict) or "kind" not in e:
            errors.append(f"event {i}: no 'kind'")
            continue
        if e["kind"] == "span":
            missing = [k for k in SPAN_KEYS if k not in e]
            if missing:
                errors.append(f"event {i}: span missing {missing}")
            elif not (0 <= e["t_start"] <= e["t_end"]
                      and abs((e["t_end"] - e["t_start"]) - e["dur_s"])
                      < 1e-6 + 1e-9 * abs(e["dur_s"])):
                errors.append(f"event {i}: non-monotonic span timestamps")
    manifests = [e for e in events if e.get("kind") == "manifest"]
    if not any(m.get("backend") for m in manifests):
        errors.append("no manifest with a backend field")
    return errors


def summarize(events, out=sys.stdout):
    spans = [e for e in events if e.get("kind") == "span"]
    agg = defaultdict(lambda: [0, 0.0])  # path -> [calls, total_s]
    rates = defaultdict(lambda: defaultdict(lambda: [0.0, 0.0]))
    for s in spans:
        a = agg[s.get("path", s.get("name", "?"))]
        a[0] += 1
        a[1] += s.get("dur_s", 0.0)
        for k, v in (s.get("counters") or {}).items():
            r = rates[s.get("path", "?")][k]
            r[0] += v
            r[1] += s.get("dur_s", 0.0)
    total = sum(a[1] for a in agg.values()) or 1.0
    print(f"{len(spans)} spans, {len(agg)} distinct paths, "
          f"{total:.3f} s total span time", file=out)
    print(f"{'path':<40} {'calls':>6} {'total_s':>10} {'mean_s':>10} "
          f"{'share':>6}", file=out)
    for path, (calls, tot) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
        print(f"{path:<40} {calls:>6} {tot:>10.3f} {tot / calls:>10.3f} "
              f"{100 * tot / total:>5.1f}%", file=out)
    if rates:
        print(f"\n{'path':<40} {'counter':<12} {'total':>14} "
              f"{'per_sec':>14}", file=out)
        for path, counters in sorted(rates.items()):
            for k, (n, dur) in sorted(counters.items()):
                rate = f"{n / dur:,.0f}" if dur > 0 else "-"
                print(f"{path:<40} {k:<12} {n:>14,.0f} {rate:>14}",
                      file=out)
    for m in (e for e in events if e.get("kind") == "manifest"):
        cfg = m.get("config") or {}
        print(f"\nmanifest: backend={m.get('backend')} "
              f"devices={m.get('device_count')}x{m.get('device_kind')} "
              f"jax={m.get('jax_version')} git={str(m.get('git_sha'))[:12]} "
              f"config={json.dumps(cfg, sort_keys=True)}", file=out)
    for e in (e for e in events if e.get("kind") == "event"):
        keys = {k: v for k, v in e.items() if k not in ("kind", "ts")}
        print(f"event: {json.dumps(keys, sort_keys=True)}", file=out)


def main(argv):
    args = [a for a in argv[1:] if not a.startswith("--")]
    if len(args) != 1:
        raise SystemExit(__doc__)
    events, bad = read_events(args[0])
    if "--validate" in argv:
        errors = validate(events, bad)
        if errors:
            for err in errors:
                print(f"INVALID: {err}", file=sys.stderr)
            raise SystemExit(1)
        print(f"valid: {len(events)} events", file=sys.stderr)
    summarize(events)


if __name__ == "__main__":
    main(sys.argv)
