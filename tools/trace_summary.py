"""Summarize (and validate) a cpr_tpu telemetry JSONL stream.

Reads the event file written via `CPR_TELEMETRY=<path>` (or
`cpr_tpu.telemetry.configure`), prints per-span aggregates — calls,
total/mean wall time, share of the total — and a throughput table for
spans carrying counters (env_steps etc.), plus schema-v2 tables for
`compile` events (per-function retrace counts and compile seconds),
`device_metrics` events (in-graph counters/stats/histograms), and
`vi_residuals` convergence trajectories, any manifests, and remaining
point events (tpu_outage, revert, ...).  The post-mortem half of the
telemetry layer: `bench.py`, the training driver, and the sweeps write
the stream; this reads it back without re-running anything.

`--validate` additionally checks the artifact is schema-complete
(every span event carries the SPAN_KEYS, typed point events carry
their EVENT_FIELDS, timestamps are monotonic non-negative intervals,
at least one manifest names its backend) and exits nonzero otherwise —
`make telemetry-smoke` runs a tiny bench and asserts through this
mode.  `--expect name[,name...]` (with --validate) further requires at
least one event of each named type in the stream, so the smoke run
fails loudly if a producer silently stops emitting.

`--run <run_id>` (instead of a path) resolves the run's primary
telemetry stream through the run archive (cpr_tpu.perf.archive;
`--archive <dir>` overrides the root, else $CPR_OBS_ARCHIVE or
runs/archive) — summarize any archived run by id without knowing
where its files landed.

Usage: python tools/trace_summary.py <telemetry.jsonl>
           [--validate] [--expect device_metrics,compile]
       python tools/trace_summary.py --run <run_id> [--archive DIR]
"""

import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from cpr_tpu.telemetry import EVENT_FIELDS, SPAN_KEYS  # noqa: E402


def read_events(path):
    events, bad = [], []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError as e:
                bad.append(f"line {i}: not JSON ({e})")
    return events, bad


def validate(events, bad, expect=()):
    """Schema-completeness errors for `--validate` (empty list = ok).
    `expect` names event types at least one of which must appear."""
    errors = list(bad)
    if not events:
        errors.append("empty event stream")
    for i, e in enumerate(events, 1):
        if not isinstance(e, dict) or "kind" not in e:
            errors.append(f"event {i}: no 'kind'")
            continue
        if e["kind"] == "span":
            missing = [k for k in SPAN_KEYS if k not in e]
            if missing:
                errors.append(f"event {i}: span missing {missing}")
            elif not (0 <= e["t_start"] <= e["t_end"]
                      and abs((e["t_end"] - e["t_start"]) - e["dur_s"])
                      < 1e-6 + 1e-9 * abs(e["dur_s"])):
                errors.append(f"event {i}: non-monotonic span timestamps")
        elif e["kind"] == "event":
            # typed point events (schema v2) carry their declared fields
            required = EVENT_FIELDS.get(e.get("name"))
            if required:
                missing = [k for k in required if k not in e]
                if missing:
                    errors.append(
                        f"event {i}: {e['name']} missing {missing}")
    manifests = [e for e in events if e.get("kind") == "manifest"]
    if not any(m.get("backend") for m in manifests):
        errors.append("no manifest with a backend field")
    names = {e.get("name") for e in events if e.get("kind") == "event"}
    for want in expect:
        if want not in names:
            errors.append(f"expected at least one '{want}' event")
    return errors


def summarize(events, out=sys.stdout):
    spans = [e for e in events if e.get("kind") == "span"]
    agg = defaultdict(lambda: [0, 0.0])  # path -> [calls, total_s]
    rates = defaultdict(lambda: defaultdict(lambda: [0.0, 0.0]))
    for s in spans:
        a = agg[s.get("path", s.get("name", "?"))]
        a[0] += 1
        a[1] += s.get("dur_s", 0.0)
        for k, v in (s.get("counters") or {}).items():
            r = rates[s.get("path", "?")][k]
            r[0] += v
            r[1] += s.get("dur_s", 0.0)
    total = sum(a[1] for a in agg.values()) or 1.0
    print(f"{len(spans)} spans, {len(agg)} distinct paths, "
          f"{total:.3f} s total span time", file=out)
    print(f"{'path':<40} {'calls':>6} {'total_s':>10} {'mean_s':>10} "
          f"{'share':>6}", file=out)
    for path, (calls, tot) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
        print(f"{path:<40} {calls:>6} {tot:>10.3f} {tot / calls:>10.3f} "
              f"{100 * tot / total:>5.1f}%", file=out)
    if rates:
        print(f"\n{'path':<40} {'counter':<12} {'total':>14} "
              f"{'per_sec':>14}", file=out)
        for path, counters in sorted(rates.items()):
            for k, (n, dur) in sorted(counters.items()):
                rate = f"{n / dur:,.0f}" if dur > 0 else "-"
                print(f"{path:<40} {k:<12} {n:>14,.0f} {rate:>14}",
                      file=out)
    _compile_table(events, out)
    _device_metrics_tables(events, out)
    _vi_residuals_lines(events, out)
    _resilience_lines(events, out)
    _supervisor_lines(events, out)
    _serve_lines(events, out)
    _alert_lines(events, out)
    _admission_lines(events, out)
    _route_lines(events, out)
    _request_lines(events, out)
    _mdp_solve_lines(events, out)
    _mdp_compile_lines(events, out)
    _attack_sweep_lines(events, out)
    _memory_lines(events, out)
    _perf_gate_lines(events, out)
    for m in (e for e in events if e.get("kind") == "manifest"):
        cfg = m.get("config") or {}
        print(f"\nmanifest: backend={m.get('backend')} "
              f"devices={m.get('device_count')}x{m.get('device_kind')} "
              f"jax={m.get('jax_version')} git={str(m.get('git_sha'))[:12]} "
              f"config={json.dumps(cfg, sort_keys=True)}", file=out)
    tabled = ("compile", "device_metrics", "vi_residuals", "retry",
              "checkpoint", "perf_gate", "supervisor", "serve",
              "request", "admission", "route", "mdp_solve",
              "mdp_compile", "attack_sweep", "alert", "memory")
    for e in (e for e in events if e.get("kind") == "event"
              and e.get("name") not in tabled):
        keys = {k: v for k, v in e.items() if k not in ("kind", "ts")}
        print(f"event: {json.dumps(keys, sort_keys=True)}", file=out)


def _compile_table(events, out):
    """Per-function compile/retrace aggregate: `count > 1` for one fn
    under stable shapes is the retrace smell the compile_watch exists
    to surface."""
    comp = [e for e in events if e.get("kind") == "event"
            and e.get("name") == "compile"]
    if not comp:
        return
    agg = defaultdict(lambda: [0, 0.0, 0.0])
    for e in comp:
        a = agg[e.get("fn", "?")]
        a[0] += 1
        a[1] += e.get("trace_s") or 0.0
        a[2] += e.get("compile_s") or 0.0
    print(f"\n{'compiled fn':<32} {'count':>6} {'trace_s':>9} "
          f"{'compile_s':>10}", file=out)
    for fn, (n, tr, co) in sorted(agg.items(), key=lambda kv: -kv[1][2]):
        print(f"{fn:<32} {n:>6} {tr:>9.3f} {co:>10.3f}", file=out)


def _device_metrics_tables(events, out):
    for e in events:
        if e.get("kind") != "event" or e.get("name") != "device_metrics":
            continue
        print(f"\ndevice_metrics scope={e.get('scope')}", file=out)
        for k, v in sorted((e.get("metrics") or {}).items()):
            if isinstance(v, dict) and "counts" in v:
                print(f"  {k:<24} counts={v['counts']}", file=out)
            elif isinstance(v, dict):
                if v.get("count"):
                    print(f"  {k:<24} n={v['count']:.0f} "
                          f"min={v['min']:.4g} max={v['max']:.4g} "
                          f"mean={v['mean']:.4g}", file=out)
                else:
                    print(f"  {k:<24} n=0", file=out)
            else:
                print(f"  {k:<24} {v}", file=out)


def _vi_residuals_lines(events, out):
    for e in events:
        if e.get("kind") != "event" or e.get("name") != "vi_residuals":
            continue
        r = e.get("residuals") or []
        head = (f"first={r[0]:.4g} last={r[-1]:.4g} " if r else "")
        print(f"\nvi_residuals impl={e.get('impl')} "
              f"n_sweeps={e.get('n_sweeps')} {head}"
              f"kept={len(r)} truncated={e.get('truncated')}", file=out)


def _resilience_lines(events, out):
    """Schema-v3 resilience aggregates: retry counts per call site and
    checkpoint writes per kind (resume/preempted/fault_injected events
    stay in the generic dump below — they are rare and each one
    matters)."""
    retries = defaultdict(lambda: [0, 0.0])
    ckpts = defaultdict(int)
    for e in events:
        if e.get("kind") != "event":
            continue
        if e.get("name") == "retry":
            a = retries[e.get("site", "?")]
            a[0] += 1
            a[1] += e.get("delay_s") or 0.0
        elif e.get("name") == "checkpoint":
            ckpts[e.get("what", "?")] += 1
    if retries:
        print(f"\n{'retried site':<32} {'retries':>8} "
              f"{'backoff_s':>10}", file=out)
        for site, (n, d) in sorted(retries.items(), key=lambda kv: -kv[1][0]):
            print(f"{site:<32} {n:>8} {d:>10.2f}", file=out)
    if ckpts:
        kinds = " ".join(f"{k}={n}" for k, n in sorted(ckpts.items()))
        print(f"\ncheckpoints written: {kinds}", file=out)


def _supervisor_lines(events, out):
    """Schema-v6 supervisor decisions (cpr_tpu/supervisor): the
    chronological probe / stall / warm-restart / escalation trail per
    supervised site — the story of how a device round degraded (or
    recovered) reads straight down this table."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "supervisor"]
    if not evs:
        return
    print(f"\n{'supervisor action':<18} {'site':<24} {'dur_s':>8} "
          f"reason", file=out)
    for e in evs:
        dur = e.get("dur_s")
        dur_txt = f"{dur:.1f}" if isinstance(dur, (int, float)) else "-"
        print(f"{str(e.get('action')):<18} {str(e.get('site')):<24} "
              f"{dur_txt:>8} {e.get('reason')}", file=out)


def _serve_lines(events, out):
    """Schema-v7 serving-layer decisions (cpr_tpu/serve): per-action
    tallies plus the drain-time report's throughput line, so a serving
    session's admit/complete churn and sustained steps/sec read off
    one block without replaying the event stream."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "serve"]
    if not evs:
        return
    counts = defaultdict(int)
    for e in evs:
        counts[str(e.get("action"))] += 1
    tally = " ".join(f"{k}={n}" for k, n in sorted(counts.items()))
    print(f"\nserve events: {tally}", file=out)
    for e in evs:
        if e.get("action") != "report":
            continue
        d = e.get("detail") or {}
        sps = d.get("steps_per_sec")
        occ = d.get("occupancy")
        sps_txt = f"{sps:,.0f}" if isinstance(sps, (int, float)) else "-"
        occ_txt = f"{occ:.2f}" if isinstance(occ, (int, float)) else "-"
        print(f"serve report: steps={d.get('steps')} "
              f"episodes={d.get('episodes')} bursts={d.get('bursts')} "
              f"ticks={d.get('ticks')} admitted={d.get('admitted')} "
              f"steps_per_sec={sps_txt} occupancy={occ_txt} "
              f"lanes={d.get('n_lanes')} burst={d.get('burst')}",
              file=out)


def _alert_lines(events, out):
    """Schema-v14 SLO burn-rate alerts (cpr_tpu/monitor/alerts): one
    aggregate line per signal x class x severity x window with the
    fire count and the worst observed burn rate — how hard and how
    often a run breached its error budgets reads off one block."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "alert"]
    if not evs:
        return
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # [n, max_burn, max_value]
    for e in evs:
        key = (str(e.get("signal")), str(e.get("cls")),
               str(e.get("severity")), e.get("window_s"))
        a = agg[key]
        a[0] += 1
        b = e.get("burn_rate")
        if isinstance(b, (int, float)):
            a[1] = max(a[1], b)
        v = e.get("value")
        if isinstance(v, (int, float)):
            a[2] = max(a[2], v)
    print(f"\n{'alert signal':<16} {'class':<12} {'severity':<9} "
          f"{'window_s':>9} {'n':>5} {'max_burn':>9} {'max_value':>10}",
          file=out)
    for (signal, cls, severity, window_s), (n, mb, mv) in sorted(
            agg.items(), key=lambda kv: str(kv[0])):
        win_txt = (f"{window_s:g}"
                   if isinstance(window_s, (int, float)) else "-")
        print(f"{signal:<16} {cls:<12} {severity:<9} {win_txt:>9} "
              f"{n:>5} {mb:>9.1f} {mv:>10.4f}", file=out)


def _admission_lines(events, out):
    """Schema-v9 admission-control refusals (cpr_tpu/serve): one line
    per shed reason x op x priority with the retry_after hint range —
    whether a loaded session shed from queue pressure or SLO breach
    (and how long it told clients to back off) reads off one block."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "admission"]
    if not evs:
        return
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # [n, sum_ra, max_ra]
    for e in evs:
        key = (str(e.get("reason")), str(e.get("op")),
               str(e.get("priority")))
        a = agg[key]
        a[0] += 1
        ra = e.get("retry_after_s")
        if isinstance(ra, (int, float)):
            a[1] += ra
            a[2] = max(a[2], ra)
    print(f"\n{'shed reason':<16} {'op':<16} {'prio':<5} {'n':>6} "
          f"{'mean_retry_s':>13} {'max_retry_s':>12}", file=out)
    for (reason, op, prio), (n, tot, mx) in sorted(agg.items()):
        mean_txt = f"{tot / n:.2f}" if n else "-"
        print(f"{reason:<16} {op:<16} {prio:<5} {n:>6} "
              f"{mean_txt:>13} {mx:>12.2f}", file=out)


def _route_lines(events, out):
    """Schema-v9 fleet routing decisions (cpr_tpu/serve/router): a
    per-action x replica tally — how traffic spread over the fleet and
    how many sessions were requeued (failover) or refused after a
    replica loss summarizes without replaying the stream."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "route"]
    if not evs:
        return
    agg = defaultdict(int)
    for e in evs:
        agg[(str(e.get("action")), str(e.get("replica")))] += 1
    print(f"\n{'route action':<14} {'replica':<8} {'n':>6}", file=out)
    for (action, replica), n in sorted(agg.items()):
        print(f"{action:<14} {replica:<8} {n:>6}", file=out)


def _request_lines(events, out):
    """Schema-v8 per-request latency events (cpr_tpu/serve): one
    aggregate line per op x role x status with mean/max latency, so a
    stream with thousands of requests still summarizes in a screen.
    Per-trace detail is tools/trace_stitch.py's job."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "request"]
    if not evs:
        return
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # [n, sum_total, max_total]
    for e in evs:
        key = (str(e.get("op")), str(e.get("role")), str(e.get("status")))
        a = agg[key]
        a[0] += 1
        t = e.get("total_s")
        if isinstance(t, (int, float)):
            a[1] += t
            a[2] = max(a[2], t)
    print(f"\n{'request op':<20} {'role':<7} {'status':<8} {'n':>6} "
          f"{'mean_s':>9} {'max_s':>9}", file=out)
    for (op, role, status), (n, tot, mx) in sorted(agg.items()):
        mean_txt = f"{tot / n:.4f}" if n else "-"
        print(f"{op:<20} {role:<7} {status:<8} {n:>6} {mean_txt:>9} "
              f"{mx:>9.4f}", file=out)


def _mdp_solve_lines(events, out):
    """Schema-v10 grid-batched exact-MDP solves (cpr_tpu/mdp/grid):
    one line per solve — grid shape, MDP size, sweep count, how many
    points converged, and the points/sec rate the perf ledger banks."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "mdp_solve"]
    if not evs:
        return
    print(f"\n{'mdp_solve':<18} {'grid':<8} {'states':>9} {'trans':>10} "
          f"{'sweeps':>7} {'conv':>6} {'solve_s':>9} {'pts/sec':>9}",
          file=out)
    for e in evs:
        g = e.get("grid") or []
        grid_txt = "x".join(str(x) for x in g) if g else "-"
        label = f"{e.get('protocol')}@{e.get('cutoff')}"
        pps = e.get("points_per_sec")
        pps_txt = f"{pps:.2f}" if isinstance(pps, (int, float)) else "-"
        sol = e.get("solve_s")
        sol_txt = f"{sol:.3f}" if isinstance(sol, (int, float)) else "-"
        print(f"{label:<18} {grid_txt:<8} {e.get('n_states'):>9} "
              f"{e.get('n_transitions'):>10} {e.get('sweeps'):>7} "
              f"{e.get('converged'):>6} {sol_txt:>9} {pps_txt:>9}",
              file=out)


def _mdp_compile_lines(events, out):
    """Schema-v12 frontier-batched MDP compiles (cpr_tpu/mdp/frontier):
    one line per compile — BFS round count, compiled MDP size, worker
    process count, resume flag, and the states/sec rate the perf
    ledger banks."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "mdp_compile"]
    if not evs:
        return
    print(f"\n{'mdp_compile':<18} {'rounds':>7} {'states':>9} "
          f"{'trans':>10} {'workers':>8} {'resumed':>8} "
          f"{'compile_s':>10} {'st/sec':>9}", file=out)
    for e in evs:
        label = f"{e.get('protocol')}@{e.get('cutoff')}"
        sps = e.get("states_per_sec")
        sps_txt = f"{sps:.1f}" if isinstance(sps, (int, float)) else "-"
        cs = e.get("compile_s")
        cs_txt = f"{cs:.3f}" if isinstance(cs, (int, float)) else "-"
        print(f"{label:<18} {e.get('rounds'):>7} {e.get('states'):>9} "
              f"{e.get('transitions'):>10} {e.get('n_workers'):>8} "
              f"{str(bool(e.get('resumed'))).lower():>8} {cs_txt:>10} "
              f"{sps_txt:>9}", file=out)


def _attack_sweep_lines(events, out):
    """Schema-v11 adversary-in-the-network sweeps
    (cpr_tpu/netsim/attack): one line per vmapped batch — protocol,
    topology, lane/policy counts, overflow drops (healthy: 0), and
    the lanes/sec rate the perf ledger banks."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "attack_sweep"]
    if not evs:
        return
    print(f"\n{'attack_sweep':<12} {'topology':<16} {'lanes':>6} "
          f"{'policies':>8} {'devs':>5} {'drops':>6} {'sweep_s':>9} "
          f"{'lanes/sec':>10}", file=out)
    for e in evs:
        sw = e.get("sweep_s")
        sw_txt = f"{sw:.3f}" if isinstance(sw, (int, float)) else "-"
        lps = e.get("lanes_per_sec")
        lps_txt = f"{lps:.2f}" if isinstance(lps, (int, float)) else "-"
        print(f"{str(e.get('protocol')):<12} "
              f"{str(e.get('topology')):<16} {e.get('lanes'):>6} "
              f"{e.get('policies'):>8} {e.get('n_devices', '-'):>5} "
              f"{e.get('drops'):>6} {sw_txt:>9} {lps_txt:>10}",
              file=out)


def _memory_lines(events, out):
    """Schema-v15 memory watermarks (telemetry.MemoryWatermark): one
    line per scope with the peak / in-use / headroom bytes and the
    predicted working set where the producer claimed one, so capacity
    planning reads measurement next to prediction."""
    evs = [e for e in events if e.get("kind") == "event"
           and e.get("name") == "memory"]
    if not evs:
        return

    def mb(v):
        return (f"{v / (1 << 20):,.1f}"
                if isinstance(v, (int, float)) else "-")

    print(f"\n{'memory scope':<14} {'source':<7} {'peak_MiB':>10} "
          f"{'in_use_MiB':>11} {'headroom_MiB':>13} "
          f"{'predicted_MiB':>14} {'samples':>8}", file=out)
    for e in evs:
        limit = e.get("limit_bytes")
        peak = e.get("peak_bytes")
        headroom = (limit - peak
                    if isinstance(limit, (int, float))
                    and isinstance(peak, (int, float)) else None)
        print(f"{str(e.get('scope')):<14} {str(e.get('source')):<7} "
              f"{mb(peak):>10} {mb(e.get('in_use_bytes')):>11} "
              f"{mb(headroom):>13} {mb(e.get('predicted_bytes')):>14} "
              f"{e.get('n_samples', '-'):>8}", file=out)


def _perf_gate_lines(events, out):
    """Schema-v5 perf-gate verdicts (cpr_tpu/perf): one line per gate,
    baseline median alongside the judged value so a WARN/FAIL is
    self-explanatory without opening the ledger."""
    gates = [e for e in events if e.get("kind") == "event"
             and e.get("name") == "perf_gate"]
    if not gates:
        return
    print(f"\n{'perf gate metric':<44} {'backend':<7} {'verdict':<7} "
          f"{'value':>14} {'baseline med':>14}", file=out)
    for e in gates:
        base = e.get("baseline") or {}
        med = base.get("median") if isinstance(base, dict) else None
        fmt = lambda v: ("-" if not isinstance(v, (int, float))  # noqa: E731
                         else f"{v:,.0f}")
        print(f"{str(e.get('metric')):<44} {str(e.get('backend')):<7} "
              f"{str(e.get('verdict')):<7} {fmt(e.get('value')):>14} "
              f"{fmt(med):>14}", file=out)


def _take_value(argv, flag):
    """Pop `--flag VALUE` or `--flag=VALUE` from the hand-rolled argv
    (this tool predates argparse on purpose: the stream path is the
    only positional)."""
    value = None
    if flag in argv:
        i = argv.index(flag)
        if i + 1 >= len(argv):
            raise SystemExit(f"{flag} needs a value")
        value = argv[i + 1]
        del argv[i:i + 2]
    for a in list(argv):
        if a.startswith(flag + "="):
            value = a.split("=", 1)[1]
            argv.remove(a)
    return value


def resolve_archived_stream(run, root=None):
    """The archived run's primary telemetry stream path, by run id."""
    from cpr_tpu.perf import archive
    rec = archive.load_run(run, root=root)
    if rec is None:
        raise SystemExit(f"run {run!r} not found in archive "
                         f"{archive.archive_dir(root)!r}")
    path = archive.primary_stream(rec)
    if path is None:
        raise SystemExit(f"archived run {run!r} has no telemetry "
                         f"stream on disk")
    return path


def main(argv):
    argv = list(argv[1:])
    expect = []
    if "--expect" in argv:
        i = argv.index("--expect")
        if i + 1 >= len(argv):
            raise SystemExit("--expect needs a comma-separated value")
        expect = argv[i + 1].split(",")
        del argv[i:i + 2]
    for a in list(argv):
        if a.startswith("--expect="):
            expect = a.split("=", 1)[1].split(",")
            argv.remove(a)
    run = _take_value(argv, "--run")
    archive_root = _take_value(argv, "--archive")
    args = [a for a in argv if not a.startswith("--")]
    if run is not None:
        if args:
            raise SystemExit("--run replaces the stream path")
        args = [resolve_archived_stream(run, archive_root)]
    if len(args) != 1:
        raise SystemExit(__doc__)
    events, bad = read_events(args[0])
    if "--validate" in argv:
        errors = validate(events, bad, expect=expect)
        if errors:
            for err in errors:
                print(f"INVALID: {err}", file=sys.stderr)
            raise SystemExit(1)
        print(f"valid: {len(events)} events", file=sys.stderr)
    summarize(events)


if __name__ == "__main__":
    main(sys.argv)
