"""Batch-scaling sweep for the DAG-family bench configs (VERDICT r3 #1).

Round-3 profiling (docs/TPU_SESSION_r03.md) showed DAG env steps are
latency-bound (~0.4-0.5 ms/op) with batch size nearly free — so the
aggregate env-steps/s should scale with n_envs until bandwidth binds.
This tool measures one (config, n_envs, n_steps) point per invocation
with separate phase timings (build/compile/per-rep) printed unbuffered,
so a watchdogged driver can see WHERE time went when a point blows a
timeout (compile growth vs execution growth vs a wedged worker).

Usage: python tools/tpu_dag_sweep.py <bk|ethereum|tailstorm> <n_envs>
           [n_steps] [chunk]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from cpr_tpu import supervisor, telemetry  # noqa: E402
from cpr_tpu.resilience import fault_point  # noqa: E402
from cpr_tpu.telemetry import now  # noqa: E402


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def measure_env(env, policy_name, n_envs, n_steps, max_steps, chunk, reps=2):
    import jax
    import numpy as np

    from cpr_tpu.params import make_params

    tele = telemetry.current()
    params = make_params(alpha=0.35, gamma=0.5, max_steps=max_steps)
    policy = env.policies[policy_name]
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    t0 = now()
    fn = env.make_episode_stats_fn(params, policy, n_steps, chunk=chunk)
    log(f"built fn in {now() - t0:.1f}s; compiling "
        f"(n_envs={n_envs} n_steps={n_steps} chunk={chunk} "
        f"capacity={env.capacity})")
    with tele.span("sweep_compile") as sp:
        stats = sp.fence(fn(keys))
    compile_s = sp.dur_s
    log(f"compile+first run {compile_s:.1f}s")
    rep_s = []
    for r in range(reps):
        with tele.span("sweep_rep", env_steps=n_envs * n_steps) as sp:
            # timing reps deliberately replay the identical key batch:
            # min-over-reps only means something if every rep runs the
            # exact same work
            # jaxlint: disable-next-line=key-reuse
            stats = sp.fence(fn(keys))
        rep_s.append(sp.dur_s)
        log(f"rep {r}: {rep_s[-1]:.1f}s "
            f"({n_envs * n_steps / rep_s[-1]:.0f} steps/s)")
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    rate = n_envs * n_steps / min(rep_s)
    return rate, atk / (atk + dfn), compile_s, min(rep_s)


def main():
    config, n_envs = sys.argv[1], int(sys.argv[2])
    n_steps = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    chunk = int(sys.argv[4]) if len(sys.argv) > 4 else 0

    # supervised-child protocol (cpr_tpu/supervisor): beat before the
    # jax import so even an init wedge is watchdogged by heartbeat, and
    # expose the `run` fault site so the smoke harness can wedge this
    # tool deterministically
    supervisor.maybe_start_heartbeat()
    fault_point("run")

    # backend bring-up is legitimately slow and silent — the "init"
    # phase is slow_ok for the parent's stall rule (wall budget only)
    with supervisor.child_phase("init"):
        import jax
        jax.config.update("jax_default_prng_impl", "threefry2x32")
        jax.config.update("jax_threefry_partitionable", True)
        log(f"backend={jax.devices()[0].platform}")

    # opt-in ring window for the active-set shapes (bench.py decides
    # the production value; the sweep honors the same knob)
    window = int(os.environ.get("CPR_WINDOW", "0")) or None
    if config == "bk":
        from cpr_tpu.envs.bk import BkSSZ
        n_steps = n_steps or 256
        env = BkSSZ(k=8, incentive_scheme="constant",
                    max_steps_hint=n_steps, window=window)
        rate, check, compile_s, rep_s = measure_env(
            env, "get-ahead", n_envs, n_steps, n_steps - 8, chunk or None)
    elif config == "ethereum":
        from cpr_tpu.envs.ethereum import EthereumSSZ
        n_steps = n_steps or 256
        env = EthereumSSZ("byzantium", max_steps_hint=n_steps,
                          window=window)
        rate, check, compile_s, rep_s = measure_env(
            env, "fn19", n_envs, n_steps, n_steps - 8, chunk or None)
    elif config == "tailstorm":
        import numpy as np
        from cpr_tpu.envs.tailstorm import TailstormSSZ
        from cpr_tpu.params import make_params
        from cpr_tpu.train.ppo import PPOConfig, make_train

        rollout = n_steps or 128
        # label bump: the measured shape changed when the ring-window
        # port landed (capacity floor + plane gating), so rows must not
        # be compared against pre-ring "tailstorm" BENCH_SCALING rows
        config = "tailstorm2"
        env = TailstormSSZ(k=8, incentive_scheme="discount",
                           max_steps_hint=128, window=window)
        params = make_params(alpha=0.35, gamma=0.5, max_steps=120)
        cfg = PPOConfig(n_envs=n_envs, n_steps=rollout)
        init_fn, train_step = make_train(env, params, cfg)
        tele = telemetry.current()
        with tele.span("sweep_compile") as sp:
            # one-shot init: jit(init_fn) is constructed and called
            # exactly once, so the fresh-cache-per-call hazard is moot
            # jaxlint: disable-next-line=jit-in-loop
            carry = jax.jit(init_fn)(jax.random.PRNGKey(0))
            step = jax.jit(train_step)
            carry, _ = step(carry)
            sp.fence(carry)
        compile_s = sp.dur_s
        log(f"compile+first {compile_s:.1f}s")
        rep_ts = []
        for r in range(2):
            with tele.span("sweep_rep",
                           env_steps=n_envs * rollout) as sp:
                carry, metrics = step(carry)
                sp.fence(carry)
            rep_ts.append(sp.dur_s)
            log(f"rep {r}: {rep_ts[-1]:.1f}s "
                f"({n_envs * rollout / rep_ts[-1]:.0f} steps/s)")
        rep_s = min(rep_ts)
        rate = n_envs * rollout / rep_s
        check = float(np.asarray(metrics["entropy"]))
    else:
        raise SystemExit(f"unknown config {config}")

    print(json.dumps({
        "config": config, "n_envs": n_envs, "n_steps": n_steps,
        "chunk": chunk or None, "window": window or 0,
        "capacity": env.capacity, "steps_per_sec": round(rate),
        "check": round(float(check), 4), "compile_s": round(compile_s, 1),
        "rep_s": round(rep_s, 1),
        # full provenance so a banked sweep row is self-describing
        "manifest": telemetry.run_manifest(config=dict(
            config=config, n_envs=n_envs, n_steps=n_steps,
            chunk=chunk or None, window=window or 0)),
    }), flush=True)


if __name__ == "__main__":
    main()
