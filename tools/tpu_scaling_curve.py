"""Batch-scaling curve for the DAG-family bench configs (VERDICT r4 #2).

Measures aggregate env-steps/s at a ladder of batch sizes per config
(one watchdogged subprocess per point, the bisect_common pattern — a
crashed worker must not take the whole curve down) and writes
BENCH_SCALING_<round>.json.  Round-4 context: the aggregate rate PEAKED
at 4-8k envs and DECLINED beyond — upside-down for a throughput device;
the active-set redesign shrinks per-step bytes so the curve should now
be monotone to >=32k envs (the verdict's done-criterion) or the point
of genuine HBM saturation.

Usage: python tools/tpu_scaling_curve.py [bk|ethereum|tailstorm ...]
"""

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LADDER = (1024, 4096, 8192, 16384, 32768, 65536)

# per-config: (n_steps, chunk) at bench shapes (bench.py CONFIGS)
SHAPES = {
    "bk": (128, 128),
    "ethereum": (128, 128),
    "tailstorm": (128, None),  # PPO train step manages its own scan
}


def measure_point(config, n_envs, timeout=600.0):
    """One subprocess measurement via tools/tpu_dag_sweep.py."""
    n_steps, chunk = SHAPES[config]
    cmd = [sys.executable, os.path.join("tools", "tpu_dag_sweep.py"),
           config, str(n_envs), str(n_steps)]
    if chunk:
        cmd.append(str(chunk))
    proc = subprocess.Popen(cmd, cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            pass
        return {"n_envs": n_envs, "error": "hung"}
    sys.stderr.write(err or "")
    lines = [ln for ln in (out or "").splitlines() if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        return {"n_envs": n_envs, "error": f"rc={proc.returncode}"}
    row = json.loads(lines[-1])
    row["n_envs"] = n_envs
    return row


def main():
    configs = sys.argv[1:] or list(SHAPES)
    rnd = os.environ.get("CPR_ROUND", "r05")
    path = os.path.join(REPO, f"BENCH_SCALING_{rnd}.json")
    curves = {}
    if os.path.exists(path):
        with open(path) as f:
            curves = json.load(f)
    for config in configs:
        rows = curves.setdefault(config, [])
        done = {r.get("n_envs") for r in rows if not r.get("error")}
        for n_envs in LADDER:
            if n_envs in done:
                continue
            t0 = time.time()
            row = measure_point(config, n_envs)
            print(f"{config} @ {n_envs}: "
                  f"{row.get('steps_per_sec', row.get('error'))} "
                  f"({time.time() - t0:.0f}s)", flush=True)
            rows[:] = [r for r in rows if r.get("n_envs") != n_envs]
            rows.append(row)
            # inline tmp+replace (the resilience.atomic_write pattern):
            # this bank is re-read on resume, so a crash mid-dump would
            # poison the whole curve — but the parent must stay jax-free
            # (each child process owns the TPU), so no cpr_tpu import
            fd, tmp = tempfile.mkstemp(dir=REPO,
                                       prefix=".bench_scaling.")
            with os.fdopen(fd, "w") as f:
                json.dump(curves, f, indent=2)
            os.replace(tmp, path)
            if row.get("error") == "hung":
                print("wedged device? stopping this config", flush=True)
                break
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
