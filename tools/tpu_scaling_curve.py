"""Batch-scaling curve for the DAG-family bench configs (VERDICT r4 #2).

Measures aggregate env-steps/s at a ladder of batch sizes per config
(one supervised subprocess per point — cpr_tpu/supervisor: heartbeat
stall detection, probe-before-run, probe-gated warm restart — so a
crashed worker costs one point, not the whole curve) and writes
BENCH_SCALING_<round>.json.  Round-4 context: the aggregate rate PEAKED
at 4-8k envs and DECLINED beyond — upside-down for a throughput device;
the active-set redesign shrinks per-step bytes so the curve should now
be monotone to >=32k envs (the verdict's done-criterion) or the point
of genuine HBM saturation.

Usage: python tools/tpu_scaling_curve.py [bk|ethereum|tailstorm ...]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# parent stays backend-free: cpr_tpu imports never initialize a device
# (each child process owns the TPU), so the shared supervisor and the
# atomic-write helpers are safe here
from cpr_tpu import supervisor  # noqa: E402
from cpr_tpu.resilience import TransientFault, atomic_write_json  # noqa: E402

LADDER = (1024, 4096, 8192, 16384, 32768, 65536)

# per-config: (n_steps, chunk) at bench shapes (bench.py CONFIGS)
SHAPES = {
    "bk": (128, 128),
    "ethereum": (128, 128),
    "tailstorm": (128, None),  # PPO train step manages its own scan
}


def measure_point(config, n_envs, timeout=600.0):
    """One supervised subprocess measurement via tools/tpu_dag_sweep.py
    (the child beats, so a wedge is caught by heartbeat stall; a hang
    earns one probe-gated warm restart before this returns an error
    row)."""
    n_steps, chunk = SHAPES[config]
    cmd = [sys.executable, os.path.join("tools", "tpu_dag_sweep.py"),
           config, str(n_envs), str(n_steps)]
    if chunk:
        cmd.append(str(chunk))
    try:
        out = supervisor.supervise(
            cmd, site=f"scaling:{config}:{n_envs}", cwd=REPO,
            config=supervisor.SupervisorConfig.from_env(
                wall_timeout_s=timeout))
    except supervisor.ProbeFailure:
        return {"n_envs": n_envs, "error": "hung",
                "note": "device probe failed before the run"}
    except supervisor.SupervisedHang:
        return {"n_envs": n_envs, "error": "hung"}
    except TransientFault as e:
        rc = getattr(e, "rc", None)
        return {"n_envs": n_envs,
                "error": f"rc={rc}" if rc is not None else str(e)}
    row = json.loads(out.payload.splitlines()[-1])
    row["n_envs"] = n_envs
    if out.restarts:
        row["restart_count"] = out.restarts
    return row


def main():
    configs = sys.argv[1:] or list(SHAPES)
    rnd = os.environ.get("CPR_ROUND", "r05")
    path = os.path.join(REPO, f"BENCH_SCALING_{rnd}.json")
    curves = {}
    if os.path.exists(path):
        with open(path) as f:
            curves = json.load(f)
    for config in configs:
        rows = curves.setdefault(config, [])
        done = {r.get("n_envs") for r in rows if not r.get("error")}
        for n_envs in LADDER:
            if n_envs in done:
                continue
            t0 = time.time()
            row = measure_point(config, n_envs)
            print(f"{config} @ {n_envs}: "
                  f"{row.get('steps_per_sec', row.get('error'))} "
                  f"({time.time() - t0:.0f}s)", flush=True)
            rows[:] = [r for r in rows if r.get("n_envs") != n_envs]
            rows.append(row)
            # this bank is re-read on resume, so a crash mid-dump would
            # poison the whole curve: atomic write only
            atomic_write_json(path, curves)
            if row.get("error") == "hung":
                # the supervisor already probed and warm-restarted once;
                # a hang surviving that means the device is really gone
                print("wedged device? stopping this config", flush=True)
                break
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
