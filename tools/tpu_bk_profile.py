"""Cost split of the bk step on chip: stub one primitive family at a
time and measure the warm episode-scan rate.

bk at 4096 envs runs the same ~35k env-steps/s as at 128 envs — fully
latency-bound on the per-step sequential op chain, so the lever is
whatever dominates that chain: top_k_by (4x per step), the
common-ancestor / height-walk while_loops, or release_chain.  Stubs
break semantics (revenue is ignored); only the rate matters.

Usage: python tools/tpu_bk_profile.py [max_candidates]
"""

import sys

# run as a script from anywhere: the tools dir is sys.path[0] only for
# direct execution, so resolve it explicitly
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from bisect_common import run_candidates  # noqa: E402

BASE = """
import time
from cpr_tpu.core import dag as D
from cpr_tpu.params import make_params
{stub}
from cpr_tpu.envs.bk import BkSSZ
env = BkSSZ(k=8, incentive_scheme="constant", max_steps_hint=512)
params = make_params(alpha=0.35, gamma=0.5, max_steps=504)
pol = env.policies["get-ahead"]
keys = jax.random.split(jax.random.PRNGKey(0), 4096)
fn = env.make_episode_stats_fn(params, pol, 128, chunk=128)
jax.block_until_ready(fn(keys))
t0 = time.time()
import numpy as np
s = fn(keys)
r = float(np.asarray(s["episode_progress"]).mean())  # force fetch
dt = time.time() - t0
print(f"{{4096*128/dt:,.0f}} steps/s (warm)")
"""

STUB_TOPK = """
def _stub_topk(score, mask, k, largest=False):
    idx = jnp.arange(k, dtype=jnp.int32)
    return idx, mask[idx]
D.top_k_by = _stub_topk
"""

STUB_CA = """
D.common_ancestor_by_height = lambda dag, a, b: jnp.int32(0)
"""

STUB_WALK = """
D.walk_back = lambda dag, tip, stop_fn: tip
D.block_at_height = lambda dag, tip, h, is_block_fn=None: tip
"""

STUB_RELEASE = """
D.release_chain = lambda dag, tip, time: D.release(
    dag, jnp.zeros((dag.capacity,), jnp.bool_).at[jnp.maximum(tip, 0)]
    .set(tip >= 0), time)
"""

CANDIDATES = [
    ("bk_control", BASE.format(stub="")),
    ("bk_stub_topk", BASE.format(stub=STUB_TOPK)),
    ("bk_stub_common_anc", BASE.format(stub=STUB_CA)),
    ("bk_stub_walks", BASE.format(stub=STUB_WALK)),
    ("bk_stub_release", BASE.format(stub=STUB_RELEASE)),
    ("bk_stub_all", BASE.format(
        stub=STUB_TOPK + STUB_CA + STUB_WALK + STUB_RELEASE)),
]

if __name__ == "__main__":
    limit = int(sys.argv[1]) if len(sys.argv) > 1 else None
    run_candidates(CANDIDATES, limit, timeout=420.0)
