"""Always-on-learning smoke (`make learn-smoke`).

Proves the cpr_tpu/learn contract end-to-end on CPU: a supervised
learner child and serve child wired into the closed sampler/learner
loop, under concurrent client flood, with zero-drain policy hot-swap
observable from the client side:

  1  an in-process bit-determinism check: two identical engines run a
     mixed scripted+net burst, one hot-swaps its net mid-run, and the
     scripted lanes stay bitwise identical — the swap perturbed only
     the swapped table entry (params-as-burst-argument, no retrace);
  2  launch `python -m cpr_tpu.learn.learner` under
     `supervisor.run_child`; its seq-0 snapshot (untrained net,
     published before the socket opens) becomes the server's
     `--policy-snapshot`, so the revenue baseline is the untrained
     policy by construction;
  3  launch `python -m cpr_tpu.serve.server` with `--learner` (feed
     drained experience) and `--learn-watch` (hot-swap on new
     `latest.json`) pointing at the learner, plus
     `--staleness-slo-s` so the staleness gauge alert plane is armed;
  4  flood: a greedy-only baseline wave, then mixed waves of
     `ppo#sample` (exploration), `honest` (demonstrations — every
     live lane records experience, so scripted lanes teach too) and
     greedy `ppo` measurement episodes.  Every `episode.run` reply
     carries the fingerprint that served it, so revenue windows group
     by snapshot exactly.  The flood keeps going until the serving
     fingerprint has rotated through >= 2 published swaps AND the
     mean greedy relative_reward under the newest fingerprint beats
     the untrained-baseline window by CPR_LEARN_MIN_GAIN — training
     measurably improved the serving policy, with zero client hangs
     and zero refused sessions along the way;
  5  SIGTERM the server (drain report must carry the learn block and
     policy fingerprint), then the learner (final publish, exit 0);
     both traces and their concatenation must pass `trace_summary
     --validate --expect learn`, the server trace must carry sample /
     feed / >= 2 swap learn events and heartbeats with
     `policy_fingerprint` + `snapshot_staleness_s`, and the drain
     report's `learn_samples_per_sec` / `learn_snapshot_staleness_s`
     rows must ingest into the perf ledger and clear the
     direction-aware regression gate.

Usage: python tools/learn_smoke.py [workdir]   (default /tmp/...)
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, ROOT)

from cpr_tpu import supervisor, telemetry  # noqa: E402
from cpr_tpu.perf.gate import gate_row, gate_summary  # noqa: E402
from cpr_tpu.perf.ledger import Ledger  # noqa: E402
from cpr_tpu.serve.protocol import ServeClient  # noqa: E402

# episode length == burst == learner window: a lane admitted at a
# burst boundary completes exactly at the burst's last step, and every
# drained window is exactly one update-ready experience window
MAX_STEPS = 64
LANES = 16
BURST = 64
HIDDEN = 16
ALPHA = 0.45
GAMMA = 0.5
LR = 3e-3
N_WORKERS = 16
BASELINE_EPISODES_PER_WORKER = 6
WAVE_CYCLE = ("ppo#sample", "honest", "ppo", "ppo#sample")
MAX_WAVES = 30
TAIL_WINDOW = 32  # greedy episodes in the trained-revenue window
MIN_SWAPS = 2
READY_TIMEOUT_S = 300.0
WALL_S = 900.0


def _log(msg):
    print(f"learn-smoke: {msg}", file=sys.stderr)


def _learner_cmd(workdir):
    return [sys.executable, "-m", "cpr_tpu.learn.learner",
            "--protocol", "nakamoto", "--max-steps", str(MAX_STEPS),
            "--publish-dir", os.path.join(workdir, "published"),
            "--hidden", str(HIDDEN), "--lr", str(LR),
            "--n-envs", str(LANES), "--n-steps", str(BURST),
            "--publish-every", "1", "--seed", "0",
            "--ready-file", os.path.join(workdir, "learner_ready.json")]


def _server_cmd(workdir, snap, learner_port):
    return [sys.executable, "-m", "cpr_tpu.serve.server",
            "--protocol", "nakamoto", "--max-steps", str(MAX_STEPS),
            "--lanes", str(LANES), "--burst", str(BURST),
            "--alpha", str(ALPHA), "--gamma", str(GAMMA),
            "--policy-snapshot", snap,
            "--learner", f"127.0.0.1:{learner_port}",
            "--learn-watch", os.path.join(workdir, "published"),
            "--staleness-slo-s", "60", "--heartbeat-s", "0.5",
            "--ready-file", os.path.join(workdir, "server_ready.json")]


def _child_env(workdir, trace):
    env = dict(os.environ, JAX_PLATFORMS="cpu", CPR_TELEMETRY=trace,
               CPR_TPU_CACHE=os.path.join(workdir, "cache"))
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _wait_ready(path, proc, what):
    deadline = time.time() + READY_TIMEOUT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            raise SystemExit(f"{what} child exited rc={proc.returncode} "
                             f"before becoming ready")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            time.sleep(0.25)
    raise SystemExit(f"{what} not ready within {READY_TIMEOUT_S:.0f}s")


def _swap_bit_determinism():
    """Two identical engines, mixed scripted+net lanes; B hot-swaps
    its net between bursts; scripted lanes must stay bitwise equal to
    A's — the ISSUE-20 zero-perturbation guarantee, asserted on real
    burst outputs rather than trusted from the unit suite."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cpr_tpu.envs import registry
    from cpr_tpu.params import make_params
    from cpr_tpu.serve.engine import ResidentEngine
    from cpr_tpu.train.ppo import ActorCritic

    n_lanes, burst, steps = 4, 16, 16
    env = registry.get_sized("nakamoto", steps)
    params = make_params(alpha=ALPHA, gamma=GAMMA, max_steps=steps)
    net = ActorCritic(env.n_actions, (8,))
    p0 = jax.device_get(net.init(
        jax.random.PRNGKey(0), jnp.zeros((1, env.observation_length))))
    p1 = jax.device_get(net.init(
        jax.random.PRNGKey(1), jnp.zeros((1, env.observation_length))))

    def build():
        eng = ResidentEngine(
            env, params, n_lanes=n_lanes, burst=burst,
            swap_policies={"ppo": (lambda w, o: net.apply(w, o)[0],
                                   p0, "fp0")},
            sample_policies=("ppo",), experience=burst)
        eng.start()
        eng.splice({lane: 100 + lane for lane in range(n_lanes)})
        return eng

    a, b = build(), build()
    ids = {0: a.policy_ids["honest"], 1: a.policy_ids["honest"],
           2: a.policy_ids["ppo"], 3: a.policy_ids["ppo#sample"]}
    a.burst_run(ids, occupancy=1.0)
    b.burst_run(ids, occupancy=1.0)
    swapped = b.swap_policy("ppo", p1, fingerprint="fp1")
    if swapped != {"swapped": True, "fingerprint": "fp1"}:
        raise SystemExit(f"hot-swap did not land: {swapped}")
    out_a = a.burst_run(ids, occupancy=1.0)
    out_b = b.burst_run(ids, occupancy=1.0)
    for lane in (0, 1):  # scripted lanes: bitwise unperturbed
        for k in out_a:
            va = np.asarray(out_a[k])[lane]
            vb = np.asarray(out_b[k])[lane]
            if not np.array_equal(va, vb):
                raise SystemExit(
                    f"hot-swap perturbed scripted lane {lane} "
                    f"field {k!r}: swap is not bit-deterministic")


def _episode(client, policy):
    r = client.request("episode.run", policy=policy)
    assert r.get("ok"), f"episode.run({policy}): {r}"
    return r


def _wave_worker(port, policies):
    """One persistent connection, sequential episodes; returns
    (fingerprint, relative_reward) for the greedy measurement runs."""
    out = []
    with ServeClient("127.0.0.1", port) as c:
        for policy in policies:
            r = _episode(c, policy)
            if policy == "ppo":
                out.append((r["policy_fingerprint"],
                            r["episode"]["relative_reward"]))
    return out


def _run_wave(port, policies):
    results = []
    with ThreadPoolExecutor(max_workers=N_WORKERS) as pool:
        jobs = [pool.submit(_wave_worker, port, policies)
                for _ in range(N_WORKERS)]
        for j in jobs:
            results.extend(j.result())
    return results


def _windows(measured):
    """Group greedy (fingerprint, revenue) pairs by fingerprint in
    first-seen order — the revenue-vs-snapshot windows."""
    order, groups = [], {}
    for fp, rev in measured:
        if fp not in groups:
            order.append(fp)
            groups[fp] = []
        groups[fp].append(rev)
    return [(fp, groups[fp]) for fp in order]


def _flood_until_improved(port, min_gain):
    """Baseline wave on the untrained snapshot, then mixed learn waves
    until a trailing all-post-swap greedy window measurably beats it.

    The improvement window is the TAIL_WINDOW newest greedy episodes
    rather than the newest single fingerprint: with --publish-every 1
    the serving fingerprint can rotate every burst, so no one
    fingerprint need accumulate a statistically useful window."""
    measured = _run_wave(port, ("ppo",) * BASELINE_EPISODES_PER_WORKER)
    base_fp = measured[0][0]
    # a swap may already land mid-wave; the baseline is strictly the
    # episodes the untrained seq-0 snapshot served
    base = [r for fp, r in measured if fp == base_fp]
    base_mean = sum(base) / len(base)
    _log(f"baseline window: {len(base)}/{len(measured)} greedy "
         f"episodes under {base_fp[:12]} mean relative_reward "
         f"{base_mean:.4f}")

    for wave in range(1, MAX_WAVES + 1):
        measured.extend(_run_wave(port, WAVE_CYCLE))
        wins = _windows(measured)
        tail = measured[-TAIL_WINDOW:]
        mean = sum(r for _, r in tail) / len(tail)
        _log(f"wave {wave}: {len(wins)} fingerprint windows seen, "
             f"trailing {len(tail)} greedy episodes mean {mean:.4f} "
             f"(baseline {base_mean:.4f})")
        if (len(wins) >= MIN_SWAPS + 1
                and len(tail) >= TAIL_WINDOW
                and all(fp != base_fp for fp, _ in tail)
                and mean >= base_mean + min_gain):
            return wins, base_mean, mean
    raise SystemExit(
        f"revenue never improved by {min_gain} over the untrained "
        f"baseline across {MAX_WAVES} waves "
        f"(windows: {[(fp[:12], len(r)) for fp, r in _windows(measured)]})")


def _learn_events(trace, role=None):
    out = []
    with open(trace) as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") == "event" and e.get("name") == "learn" \
                    and (role is None or e.get("role") == role):
                out.append(e)
    return out


def _serve_events(trace, action):
    out = []
    with open(trace) as f:
        for line in f:
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") == "event" and e.get("name") == "serve" \
                    and e.get("action") == action:
                out.append(e)
    return out


def _validate_stream(trace, expect):
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "trace_summary.py")
    r = subprocess.run(
        [sys.executable, tool, trace, "--validate", "--expect", expect],
        capture_output=True, text=True)
    if r.returncode != 0:
        sys.stderr.write(r.stdout + r.stderr)
        raise SystemExit(f"telemetry validation failed for {trace}")


def _check_server_trace(trace):
    swaps = _learn_events(trace, "swap")
    if len(swaps) < MIN_SWAPS:
        raise SystemExit(f"only {len(swaps)} swap learn events in the "
                         f"server trace (need >= {MIN_SWAPS})")
    for role in ("sample", "feed"):
        if not _learn_events(trace, role):
            raise SystemExit(f"no {role!r} learn event in server trace")
    hb = _serve_events(trace, "heartbeat")
    beat = (hb[-1].get("detail") or {}) if hb else {}
    if "policy_fingerprint" not in beat \
            or "snapshot_staleness_s" not in beat:
        raise SystemExit("heartbeat lacks policy_fingerprint / "
                         "snapshot_staleness_s")
    if not isinstance(beat["snapshot_staleness_s"], (int, float)):
        raise SystemExit(f"heartbeat staleness not numeric: {beat}")
    reports = _serve_events(trace, "report")
    detail = (reports[-1].get("detail") or {}) if reports else {}
    learn = detail.get("learn")
    if not isinstance(learn, dict) or not learn.get("samples"):
        raise SystemExit(f"drain report carries no learn block: "
                         f"{sorted(detail)}")
    if not detail.get("policy_fingerprint"):
        raise SystemExit("drain report lacks policy_fingerprint")
    return len(swaps), learn


def _check_learner_trace(trace):
    updates = _learn_events(trace, "update")
    publishes = _learn_events(trace, "publish")
    # seq-0 plus one per swap the server applied, at minimum
    if len(updates) < MIN_SWAPS or len(publishes) < MIN_SWAPS + 1:
        raise SystemExit(f"learner trace thin: {len(updates)} updates, "
                         f"{len(publishes)} publishes")
    return len(updates), len(publishes)


# ledger rows the drain report must bank; staleness gates with the
# flipped lower-is-better band (cpr_tpu/perf/gate.py)
_REQUIRED_METRICS = ("learn_samples_per_sec", "learn_snapshot_staleness_s")


def _bank_and_gate(workdir, trace):
    ledger = Ledger(os.path.join(workdir, "perf_ledger.jsonl"))
    n = ledger.ingest_trace(trace)
    records = ledger.records()
    results = []
    for metric in _REQUIRED_METRICS:
        rows = [r for r in records if r.get("metric") == metric]
        if not rows:
            raise SystemExit(f"no {metric} row reached the ledger")
        results.extend(gate_row(r, records) for r in rows)
    summary = gate_summary(results)
    if not summary["ok"]:
        raise SystemExit(f"learn perf gate failed: {results}")
    return n, summary


def main():
    work = sys.argv[1] if len(sys.argv) > 1 else "/tmp/cpr-learn-smoke"
    os.makedirs(work, exist_ok=True)
    server_trace = os.path.join(work, "server.jsonl")
    learner_trace = os.path.join(work, "learner.jsonl")
    client_trace = os.path.join(work, "client.jsonl")
    for p in (server_trace, learner_trace, client_trace,
              os.path.join(work, "learner_ready.json"),
              os.path.join(work, "server_ready.json")):
        if os.path.exists(p):
            os.remove(p)
    telemetry.configure(client_trace)
    telemetry.current().manifest(dict(role="learn-smoke-client"))

    import jax

    jax.config.update("jax_platforms", "cpu")
    _swap_bit_determinism()
    _log("hot-swap bit-determinism holds on scripted lanes")

    boxes = {"learner": {}, "server": {}}
    threads = {}

    def launch(name, cmd, trace):
        started = threading.Event()
        box = boxes[name]

        def on_start(proc):
            box["proc"] = proc
            started.set()

        def supervise():
            box["attempt"] = supervisor.run_child(
                cmd, wall_timeout_s=WALL_S, quiet_s=30.0,
                heartbeat_s=1.0, env=_child_env(work, trace), cwd=ROOT,
                on_start=on_start)

        threads[name] = threading.Thread(target=supervise)
        threads[name].start()
        if not started.wait(30.0):
            raise SystemExit(f"run_child never spawned the {name}")
        return box["proc"]

    def reap(name):
        proc = boxes[name].get("proc")
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
        threads[name].join(120.0)
        if threads[name].is_alive():
            raise SystemExit(f"{name} child did not drain within 120s")
        attempt = boxes[name]["attempt"]
        if attempt.status != "ok" or attempt.rc != 0:
            raise SystemExit(f"{name} child did not exit cleanly "
                             f"(status={attempt.status} rc={attempt.rc})")

    try:
        proc = launch("learner", _learner_cmd(work), learner_trace)
        lready = _wait_ready(os.path.join(work, "learner_ready.json"),
                             proc, "learner")
        snap0 = os.path.join(work, "published", "snapshot-000000.msgpack")
        if not os.path.exists(snap0):
            raise SystemExit(f"learner ready but no seq-0 snapshot "
                             f"at {snap0}")
        _log(f"learner ready on port {lready['port']} "
             f"(seq-0 snapshot published)")

        proc = launch("server", _server_cmd(work, snap0, lready["port"]),
                      server_trace)
        sready = _wait_ready(os.path.join(work, "server_ready.json"),
                             proc, "server")
        port = sready["port"]
        _log(f"server ready on port {port} (pid {sready['pid']}), "
             f"serving the untrained seq-0 snapshot")

        min_gain = float(os.environ.get("CPR_LEARN_MIN_GAIN", "0.01"))
        wins, base_mean, final_mean = _flood_until_improved(port, min_gain)
        _log(f"revenue improved across {len(wins) - 1} hot-swaps: "
             f"{base_mean:.4f} -> {final_mean:.4f} "
             f"(+{final_mean - base_mean:.4f}, floor +{min_gain})")
    except BaseException:
        # don't leave orphans burning the wall budget
        for box in boxes.values():
            proc = box.get("proc")
            if proc is not None and proc.poll() is None:
                proc.kill()
        raise
    # drain order matters: the server's drain closes the feeder, then
    # the learner's drain runs its final publish on a quiet socket
    reap("server")
    reap("learner")
    _log("SIGTERM drained both children cleanly (exit 0)")

    n_swaps, learn_block = _check_server_trace(server_trace)
    n_updates, n_publishes = _check_learner_trace(learner_trace)
    _log(f"traces: {n_swaps} swaps / {learn_block['samples']} samples "
         f"fed on the serve side; {n_updates} updates / "
         f"{n_publishes} publishes on the learner side")
    telemetry.configure(None)  # close the client sink before merging
    _validate_stream(server_trace, "serve,learn")
    _validate_stream(learner_trace, "learn")
    from cpr_tpu import resilience

    merged = os.path.join(work, "merged.jsonl")
    resilience.atomic_write_text(merged, "".join(
        open(p).read()
        for p in (server_trace, learner_trace, client_trace)))
    _validate_stream(merged, "serve,learn,request")
    _log("trace validation clean (server, learner, merged)")

    n_banked, summary = _bank_and_gate(work, server_trace)
    print(f"learn-smoke: PASS (revenue {base_mean:.4f} -> "
          f"{final_mean:.4f} across {n_swaps} zero-drain hot-swaps; "
          f"{n_updates} learner updates on "
          f"{learn_block['samples']} fleet-sampled steps; banked "
          f"{n_banked} ledger rows; gate {summary})")


if __name__ == "__main__":
    main()
