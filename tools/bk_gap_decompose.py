"""Mechanism decomposition of the bk get-ahead cross-engine gap
(VERDICT r3 weak #4 / next #5).

The pinned deviation (tests/test_oracle_equivalence.py): at alpha=0.45,
gamma=0.5 the C++ simulator's BkAgent earns oracle - env = +0.0445 at
k=1 and -0.0325 at k=4 relative revenue vs the JAX env.

Hypothesis under test: the gap is GYM-vs-SIMULATOR interaction
granularity, present in the reference too — the gym engine
(engine.ml:97-273) gives the attacker a separate `Append` interaction
immediately after its own proposal is appended (same simulated time), so
a gym policy reacts one event EARLIER than the simulator's event-driven
agent, which only re-acts at the next PoW/delivery event.  The JAX env
implements gym semantics; the oracle implements simulator semantics.

Experiment: BkAgent policy "get-ahead-appendint" re-runs its action
logic after appending a proposal (at unchanged sim time) — the gym
granularity grafted onto the simulator.  If the hypothesis holds, the
appendint oracle moves toward the env number at k=1 (where proposals
complete on every vote and the extra interaction fires constantly).

Usage: python tools/bk_gap_decompose.py [acts] [n_envs]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def oracle_share(policy, k, alpha, acts, seeds=5):
    from cpr_tpu.native import OracleSim

    vals = []
    for seed in range(seeds):
        s = OracleSim(protocol="bk", k=k, scheme="constant",
                      topology="selfish_mining", alpha=alpha, gamma=0.5,
                      attacker_policy=policy, seed=seed + 1)
        s.run(acts)
        # attacker share over ALL nodes (the defender cloud has
        # ceil(1/(1-gamma)) members, not one)
        r = s.rewards(8)
        s.close()
        vals.append(r[0] / max(sum(r), 1e-9))
    m = sum(vals) / len(vals)
    sd = (sum((v - m) ** 2 for v in vals) / max(len(vals) - 1, 1)) ** 0.5
    return m, sd


def env_share(k, alpha, n_envs, max_steps=192):
    import jax
    import numpy as np

    from cpr_tpu.envs.bk import BkSSZ
    from cpr_tpu.params import make_params

    env = BkSSZ(k=k, incentive_scheme="constant", max_steps_hint=max_steps)
    params = make_params(alpha=alpha, gamma=0.5, max_steps=max_steps)
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    f = jax.jit(jax.vmap(lambda key: env.episode_stats(
        key, params, env.policies["get-ahead"], max_steps + 32)))
    st = jax.block_until_ready(f(keys))
    a = np.asarray(st["episode_reward_attacker"]).mean()
    d = np.asarray(st["episode_reward_defender"]).mean()
    return a / (a + d)


def main():
    acts = int(sys.argv[1]) if len(sys.argv) > 1 else 60_000
    n_envs = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    alpha = 0.45
    for k in (1, 4):
        o, o_sd = oracle_share("get-ahead", k, alpha, acts)
        oa, oa_sd = oracle_share("get-ahead-appendint", k, alpha, acts)
        j = env_share(k, alpha, n_envs)
        closed = abs(oa - j) / max(abs(o - j), 1e-9)
        print(f"k={k}: oracle={o:.4f}(sd {o_sd:.4f})  "
              f"oracle+appendint={oa:.4f}(sd {oa_sd:.4f})  env={j:.4f}  "
              f"gap {o - j:+.4f} -> {oa - j:+.4f} "
              f"({(1 - closed) * 100:.0f}% closed)", flush=True)


if __name__ == "__main__":
    main()
