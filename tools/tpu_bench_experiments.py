"""Bench experiments to run when the TPU is healthy: PRNG-implementation
sweep on the exact bench.py workload.

threefry (JAX default) is counter-based and compute-heavy; rbg uses the
hardware RNG path and often doubles rollout throughput on TPU.  Results
print one line per config; fold winners into bench.py (the measurement
and the SM1-vs-ES'14 guard are shared via bench.measure_nakamoto, so
numbers transfer 1:1).

Usage: python tools/tpu_bench_experiments.py [n_envs]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main():
    import jax

    from bench import SM1_GUARD, measure_nakamoto

    n_envs = int(sys.argv[1]) if len(sys.argv) > 1 else 131072
    # prng impl only affects trace-time key types; each run builds a
    # fresh trace, so one process can sweep all configs.  partitionable
    # threefry skips the global-layout key broadcast, which matters for
    # vmap'd per-env key splitting
    for prng, part in (("threefry2x32", False), ("threefry2x32", True),
                       ("rbg", False)):
        jax.config.update("jax_default_prng_impl", prng)
        jax.config.update("jax_threefry_partitionable", part)
        steps_per_sec, rel, _ = measure_nakamoto(n_envs)
        ok = SM1_GUARD[0] < rel < SM1_GUARD[1]
        print(f"prng={prng} partitionable={part} n_envs={n_envs}: "
              f"{steps_per_sec / 1e6:.0f}M steps/s (SM1 rel {rel:.4f} "
              f"guard {'ok' if ok else 'FAIL'})", flush=True)


if __name__ == "__main__":
    main()
