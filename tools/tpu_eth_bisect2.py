"""Stage-2 ethereum-fault bisect: shape grid + construct stubs.

Stage 1 (tools/tpu_eth_bisect.py) showed every construct passes at
64 envs / capacity 72, and the crash needs the full bench shape
(4096 envs, max_steps_hint=256 -> capacity 264, 256-step scan).  Stage 2
separates the axes: env count, DAG capacity, scan length, policy, and —
at the crashing shape — stubs chain_window / uncle selection to find
which kernel actually faults.

Usage: python tools/tpu_eth_bisect2.py [max_candidates]
"""

import sys

# run as a script from anywhere: the tools dir is sys.path[0] only for
# direct execution, so resolve it explicitly
sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)))
from bisect_common import run_candidates  # noqa: E402


def scan(n_envs, hint, n_steps, policy="fn19", stub=""):
    return f"""
from cpr_tpu.envs.ethereum import EthereumSSZ
from cpr_tpu.params import make_params
env = EthereumSSZ("byzantium", max_steps_hint={hint})
params = make_params(alpha=0.35, gamma=0.5, max_steps={hint} - 8)
{stub}
pol = env.policies["{policy}"]
keys = jax.random.split(jax.random.PRNGKey(0), {n_envs})
f = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, pol, {n_steps})))
stats = jax.block_until_ready(f(keys))
print(float(stats["episode_progress"].mean()))"""


STUB_WINDOW = """
_B = env.capacity
def _stub_window(dag, head):
    z = jnp.zeros((_B,), jnp.bool_)
    return z, z.at[jnp.maximum(head, 0)].set(head >= 0)
env.chain_window = _stub_window"""

STUB_SELECT = """
def _stub_select(dag, cand_mask, own_mask):
    idx = jnp.zeros((env.max_uncles,), jnp.int32)
    return idx, jnp.zeros((env.max_uncles,), jnp.bool_)
env.select_uncles = _stub_select"""

CANDIDATES = [
    # axis: env count at small capacity
    ("envs4096_hint64", scan(4096, 64, 64)),
    # axis: capacity at small env count
    ("envs256_hint256", scan(256, 256, 256)),
    # axis: middle ground
    ("envs1024_hint256", scan(1024, 256, 256)),
    ("envs4096_hint128", scan(4096, 128, 128)),
    # the crashing shape, honest policy (is it the fn19 path?)
    ("crash_shape_honest", scan(4096, 256, 256, policy="honest")),
    # the crashing shape with ethereum-specific kernels stubbed
    ("crash_shape_stub_window", scan(4096, 256, 256, stub=STUB_WINDOW)),
    ("crash_shape_stub_select", scan(4096, 256, 256, stub=STUB_SELECT)),
    # control: the known-crashing shape, unmodified (run LAST)
    ("crash_shape_control", scan(4096, 256, 256)),
]

if __name__ == "__main__":
    limit = int(sys.argv[1]) if len(sys.argv) > 1 else None
    run_candidates(CANDIDATES, limit)
